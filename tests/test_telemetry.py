"""utils.telemetry: typed metric registry (Counter/Gauge/Histogram with
labels), Prometheus/JSON exporters, monitor-stat bridge, in-process
/metrics handler, XLA compile tracking, and the hapi TelemetryCallback.

Tests use PRIVATE Registry instances wherever possible so they don't
disturb the process-wide default registry other suites accumulate into.
"""
import json

import pytest

from paddle_tpu.utils import monitor, telemetry
from paddle_tpu.utils.telemetry import (Counter, Gauge, Histogram,
                                        Registry, exponential_buckets)


# ---------------------------------------------------------------- registry
def test_counter_gauge_basics():
    reg = Registry()
    c = reg.counter("reqs_total", "requests", labelnames=("state",))
    c.labels(state="ok").inc()
    c.labels("ok").inc(2)          # positional == keyword
    c.labels(state="err").inc()
    assert c.labels(state="ok").value() == 3
    assert c.labels(state="err").value() == 1
    with pytest.raises(ValueError, match="only go up"):
        c.labels(state="ok").inc(-1)

    g = reg.gauge("depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4
    g.set_max(10)
    g.set_max(7)                   # running max keeps 10
    assert g.value() == 10


def test_get_or_create_and_conflicts():
    reg = Registry()
    a = reg.counter("dup_total", labelnames=("k",))
    b = reg.counter("dup_total", labelnames=("k",))
    assert a is b                  # modules re-declare at import safely
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("dup_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("dup_total", labelnames=("other",))


def test_histogram_bucket_mismatch_raises():
    """Silently handing caller B metric A's buckets would collapse B's
    observations into +Inf; mismatched buckets must raise like any other
    re-registration conflict."""
    reg = Registry()
    a = reg.histogram("op_seconds")
    assert reg.histogram("op_seconds") is a          # same buckets: fine
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("op_seconds", buckets=exponential_buckets(1, 2, 10))


def test_name_and_label_validation():
    reg = Registry()
    for bad in ("CamelCase", "9starts_with_digit", "has-dash", "", None):
        with pytest.raises(ValueError, match="snake_case"):
            reg.counter(bad)
    c = reg.counter("ok_total", labelnames=("a", "b"))
    with pytest.raises(ValueError, match="unexpected"):
        c.labels(a="1", z="2")
    with pytest.raises(ValueError, match="takes labels"):
        c.labels("only-one")
    with pytest.raises(ValueError, match="has labels"):
        c.inc()                    # labeled metric needs .labels()


def test_histogram_buckets_and_percentiles():
    h = Histogram("lat_seconds", buckets=exponential_buckets(0.001, 2, 10))
    for v in (0.0005, 0.0015, 0.003, 0.003, 0.02, 5.0):
        h.observe(v)
    assert h.count() == 6
    assert h.sum() == pytest.approx(5.028)
    buckets = h.bucket_counts()
    assert buckets[-1] == (None, 6)          # +Inf cumulative == count
    cums = [c for _, c in buckets]
    assert cums == sorted(cums)              # cumulative is monotone
    # percentiles are bucket-interpolated, clamped to observed [min,max]
    assert 0.0005 <= h.percentile(0) <= 0.0015
    assert 0.001 <= h.percentile(50) <= 0.004
    assert h.percentile(100) == pytest.approx(5.0)
    assert Histogram("empty_seconds").percentile(50) is None
    with pytest.raises(ValueError, match="distinct and increasing"):
        Histogram("bad_seconds", buckets=(2.0, 1.0))


def test_bounded_memory_under_many_observations():
    """The whole point of the rebase off raw sample lists: observation
    count must not grow per-sample state."""
    h = Histogram("flood_seconds", buckets=exponential_buckets(0.001, 2, 4))
    child = h.labels()
    for i in range(10_000):
        h.observe((i % 100) / 1000.0)
    assert h.count() == 10_000
    assert len(child._counts) == 5           # 4 bounds + overflow, still


def test_prometheus_render_format():
    reg = Registry()
    c = reg.counter("hits_total", "hits by kind", labelnames=("kind",))
    c.labels(kind='we"ird\nname').inc(3)
    reg.histogram("t_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.render_prometheus(include_monitor=False)
    assert "# HELP hits_total hits by kind" in text
    assert "# TYPE hits_total counter" in text
    assert 'hits_total{kind="we\\"ird\\nname"} 3' in text
    assert 't_seconds_bucket{le="0.1"} 0' in text
    assert 't_seconds_bucket{le="1"} 1' in text
    assert 't_seconds_bucket{le="+Inf"} 1' in text
    assert "t_seconds_sum 0.5" in text
    assert "t_seconds_count 1" in text


def test_snapshot_is_json_and_monitor_bridge():
    reg = Registry()
    reg.counter("x_total").inc(2)
    monitor.stat_add("bridge_stat_demo", 9)
    try:
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["metrics"]["x_total"]["series"][0]["value"] == 2
        assert snap["monitor"]["bridge_stat_demo"] == 9
        text = reg.render_prometheus()
        assert "# TYPE bridge_stat_demo untyped" in text
        assert "bridge_stat_demo 9" in text
        # typed metrics shadow same-name monitor stats (no dup families)
        monitor.stat_set("x_total", 777)
        assert reg.render_prometheus().count("# TYPE x_total") == 1
    finally:
        monitor.stat_reset("bridge_stat_demo")
        monitor.stat_reset("x_total")


def test_reset_keeps_registrations_and_child_handles():
    reg = Registry()
    c = reg.counter("r_total")
    child = c.labels()
    child.inc(5)
    h = reg.histogram("r_seconds")
    h.observe(1.0)
    reg.reset()
    assert c.value() == 0 and h.count() == 0
    child.inc()                    # cached handle still live after reset
    assert c.value() == 1


def test_non_finite_values_render_instead_of_crashing():
    """A diverged train_loss (NaN/Inf gauge) must not take down /metrics
    or make /metrics.json unparseable."""
    reg = Registry()
    reg.gauge("diverged_loss").set(float("nan"))
    reg.gauge("exploded_loss").set(float("inf"))
    text = reg.render_prometheus(include_monitor=False)
    assert "diverged_loss NaN" in text
    assert "exploded_loss +Inf" in text
    snap = json.loads(json.dumps(reg.snapshot(), allow_nan=False))
    vals = {n: m["series"][0]["value"] for n, m in snap["metrics"].items()}
    assert vals == {"diverged_loss": "NaN", "exploded_loss": "+Inf"}
    # histograms drop non-finite samples rather than poison sum/min/max
    h = reg.histogram("h_seconds")
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(0.5)
    assert h.count() == 1 and h.sum() == 0.5
    json.dumps(reg.snapshot(), allow_nan=False)


def test_value_read_does_not_create_series():
    reg_metric = telemetry.counter("peek_demo_total", labelnames=("k",))
    reg_metric.labels(k="real").inc()
    assert telemetry.value("peek_demo_total", {"k": "real"}) == 1
    # a typo'd / premature read returns default and mints NO series
    assert telemetry.value("peek_demo_total", {"k": "typo"}, 0) == 0
    assert reg_metric.peek(k="typo") is None
    text = telemetry.render_prometheus(include_monitor=False)
    assert 'peek_demo_total{k="typo"}' not in text
    assert telemetry.value("missing_metric_total", default=7) == 7
    telemetry.REGISTRY.unregister("peek_demo_total")


# ------------------------------------------------------- /metrics handler
def test_http_handler_inline_metrics_and_healthz():
    reg = Registry()
    reg.counter("served_total").inc(4)
    status, headers, body = telemetry.http_get_inline("/metrics",
                                                      registry=reg)
    assert status == 200
    assert headers["content-type"].startswith("text/plain")
    assert int(headers["content-length"]) == len(body)
    assert b"served_total 4" in body

    status, _, body = telemetry.http_get_inline(
        "/healthz", registry=reg, health_fn=lambda: {"slots": 2})
    payload = json.loads(body)
    assert status == 200 and payload["status"] == "ok"
    assert payload["slots"] == 2

    status, _, body = telemetry.http_get_inline("/metrics.json",
                                                registry=reg)
    assert status == 200
    assert json.loads(body)["metrics"]["served_total"]["kind"] == "counter"

    assert telemetry.http_get_inline("/nope", registry=reg)[0] == 404


def test_healthz_degrades_on_broken_health_fn():
    def boom():
        raise RuntimeError("engine wedged")

    status, _, body = telemetry.http_get_inline(
        "/healthz", registry=Registry(), health_fn=boom)
    payload = json.loads(body)
    assert status == 503           # status-code probes must fail too
    assert payload["status"] == "degraded"
    assert "engine wedged" in payload["error"]


def test_healthz_non_ok_state_is_503():
    """A health_fn reporting degraded/draining fails the probe at the
    HTTP layer — load balancers that only check the status code stop
    routing without parsing the body."""
    for state in ("degraded", "draining"):
        status, _, body = telemetry.http_get_inline(
            "/healthz", registry=Registry(),
            health_fn=lambda s=state: {"status": s})
        assert status == 503
        assert json.loads(body)["status"] == state


def test_metrics_server_real_socket():
    """Background ThreadingHTTPServer on a free port, exercised over a
    real loopback socket."""
    import urllib.request
    reg = Registry()
    reg.gauge("live_gauge").set(1)
    srv = telemetry.MetricsServer(registry=reg, port=0)
    try:
        srv.start()
        assert srv.port > 0
        body = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=10).read()
        assert b"live_gauge 1" in body
    finally:
        srv.stop()


# ----------------------------------------------------- compile tracking
def test_track_compiles_attributes_jit_compilation():
    import jax
    import jax.numpy as jnp

    before = telemetry.compile_count("telemetry_test_fn")
    fn = telemetry.instrument_jit(jax.jit(lambda x: x * 3 + 1),
                                  "telemetry_test_fn")
    out = fn(jnp.arange(4.0))
    fn(jnp.arange(4.0))            # cached call: no new compile
    assert float(out[1]) == 4.0
    assert fn._cache_size() == 1   # proxy passes jit internals through
    assert telemetry.compile_count("telemetry_test_fn") == before + 1
    # new dtype -> second executable -> counter follows _cache_size
    fn(jnp.arange(4, dtype=jnp.int32))
    assert telemetry.compile_count("telemetry_test_fn") == before + 2
    assert fn._cache_size() == 2


def test_track_compiles_context_manager_scopes_attribution():
    import jax
    import jax.numpy as jnp

    x = jnp.arange(3.0)            # built OUTSIDE the scope: its own tiny
    before = telemetry.compile_count("telemetry_scoped")   # compile stays
    with telemetry.track_compiles("telemetry_scoped"):     # unattributed
        jax.jit(lambda x: x - 7)(x)
    assert telemetry.compile_count("telemetry_scoped") == before + 1
    with pytest.raises(ValueError, match="snake_case"):
        with telemetry.track_compiles("Bad-Label"):
            pass


# ----------------------------------------------------- request tracing
def test_trace_request_no_dangling_events_across_profiler_restart():
    """A request straddling stop_profiler()/start_profiler() must not
    emit span-ends or flow-finishes whose partners died with the old
    trace buffer (trace-generation guard)."""
    from paddle_tpu.utils import profiler as prof

    class R:
        request_id = trace_id = 77

    r = R()
    prof.start_profiler()
    telemetry.trace_request(r, "QUEUED")
    telemetry.trace_request(r, "PREFILL")
    prof.stop_profiler()             # first trace (with 's' flow) discarded
    prof.start_profiler()            # fresh buffer, new generation
    telemetry.trace_request(r, "DECODE")
    telemetry.trace_request(r, "DONE", reason="eos")
    events = [e for e in prof._raw_events if e.get("id") == 77]
    prof.stop_profiler()
    phases = [e["ph"] for e in events]
    assert phases == ["b", "e"]      # DECODE span opens AND closes here
    assert all(e["name"] == "DECODE" for e in events)
    # no flow 't'/'f' referencing the 's' that lives in the dead trace
    assert not [e for e in events if e["ph"] in "stf"]


# -------------------------------------------------- training callback
def test_telemetry_callback_records_step_loss_and_memory():
    from paddle_tpu.hapi.callbacks import TelemetryCallback

    cb = TelemetryCallback(memory_freq=1)
    steps0 = telemetry.value("train_steps_total", default=0)
    n0 = telemetry.value("train_step_seconds", default=0)
    for step, loss in enumerate([0.5, [0.25], 0.125]):
        cb.on_train_batch_begin(step)
        cb.on_train_batch_end(step, {"loss": loss})
    assert telemetry.value("train_steps_total") == steps0 + 3
    assert telemetry.value("train_step_seconds") == n0 + 3
    assert telemetry.value("train_loss") == pytest.approx(0.125)
    cb.on_train_end()              # device-memory poll must not raise
    # CPU jax has no PJRT memory stats: the gauge is SKIPPED (None), not
    # published as a misleading zero; on accelerators it's >= 0
    mem = telemetry.value("device_bytes_in_use")
    assert mem is None or mem >= 0
