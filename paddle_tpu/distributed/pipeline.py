"""Pipeline parallelism engine (ref fluid/optimizer.py:3718 PipelineOptimizer +
framework/section_worker.cc 1F1B micro loop + device_guard placement).

TPU-native design: pipeline stages live on a 'pp' mesh axis. Activations cross
stage boundaries with lax.ppermute over ICI neighbors inside shard_map. The
micro-batch schedule is GPipe-style expressed as a lax.scan over microbatches
(compiler sees the whole schedule and overlaps permutes with compute), with
gradient accumulation across microbatches. Full engine lands with the hybrid
milestone; _CURRENT_STAGE backs static.device_guard placement markers.
"""
import contextvars

_CURRENT_STAGE = contextvars.ContextVar("pp_stage", default=None)


def current_stage():
    return _CURRENT_STAGE.get()
