"""Pipeline parallelism engine (ref fluid/optimizer.py:3718 PipelineOptimizer +
framework/section_worker.cc micro-batch loop + device_guard placement,
framework/pipeline_trainer.cc).

TPU-native redesign — NOT a port of SectionWorker threads + send/recv ops:

  - Stages live on the 'pp' mesh axis. All homogeneous blocks' params are
    stacked with a leading [num_stages] dim sharded over 'pp'
    (vmap-over-stages — the "circular buffer" pipeline formulation).
  - The micro-batch schedule is ONE lax.scan over ticks. Each tick every
    stage applies its chunk (an inner lax.scan over layers-per-stage) to its
    resident activation, the last stage's activation is emitted, and the
    activation buffer rotates with jnp.roll along the stage dim — which the
    XLA SPMD partitioner lowers to a CollectivePermute over ICI neighbors
    (the send_v2/recv_v2 analog, compiler-scheduled and overlapped).
  - Backward is plain autodiff through the scan: XLA transposes the roll to
    the reverse permute, giving the cooldown-mirrored backward schedule.
    jax.checkpoint around the per-layer body keeps activation memory at
    one tick per stage (the reference's recompute+pipeline composition).
  - Because this is pure GSPMD (no shard_map), it composes freely with
    'dp' batch sharding and Megatron 'mp' PartitionSpec hints on the
    block weights; collectives for all three ride ICI together.

Bubble fraction is the GPipe (S-1)/(M+S-1); drive it down with more
micro-batches. The warmup/cooldown ticks compute on zero garbage — that IS
the bubble, made explicit.
"""
import contextvars

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import state
from ..framework.tensor import Tensor
from . import mesh as mesh_mod
from .sharded import _valid_spec

_CURRENT_STAGE = contextvars.ContextVar("pp_stage", default=None)


def current_stage():
    return _CURRENT_STAGE.get()


class device_guard:
    """ref fluid.device_guard('gpu:k') placement marker: records the pipeline
    stage for layers built inside. Kept for API parity; the stacked-stage
    engine below derives placement from block order instead."""

    def __init__(self, device=None):
        self.stage = None
        if device is not None and ":" in str(device):
            self.stage = int(str(device).split(":")[1])

    def __enter__(self):
        self._tok = _CURRENT_STAGE.set(self.stage)
        return self

    def __exit__(self, *exc):
        _CURRENT_STAGE.reset(self._tok)
        return False


# --------------------------------------------------------------------------
# parameter stacking helpers
# --------------------------------------------------------------------------

def stack_block_params(blocks):
    """Stack per-block param dicts of a homogeneous LayerList into one dict of
    [L, ...] arrays (leading dim = layer)."""
    per = [{n: p._data for n, p in blk.named_parameters()} for blk in blocks]
    return {n: jnp.stack([d[n] for d in per]) for n in per[0]}


def unstack_block_params(blocks, stacked):
    for i, blk in enumerate(blocks):
        named = dict(blk.named_parameters())
        for n, arr in stacked.items():
            named[n]._data = jnp.copy(arr[i])


def _stacked_spec(hint, mesh, shape, pp_axis):
    """[S, Lps, ...rest] sharding: 'pp' on stage dim + the block's own
    (validated) mp hints shifted right by the two stacking dims."""
    rest_shape = shape[2:]
    parts = [None] * len(rest_shape)
    if hint is not None:
        for i, p in enumerate(list(hint)[:len(rest_shape)]):
            if (p in mesh.axis_names and rest_shape[i] % mesh.shape[p] == 0):
                parts[i] = p
    return P(pp_axis, None, *parts)


# --------------------------------------------------------------------------
# model partitioning spec
# --------------------------------------------------------------------------

class PipelineParts:
    """How a model maps onto pipeline stages — decouples the engines from any
    particular model class (the reference marks placement with device_guard;
    here the decomposition is explicit):

      pre:    Layer: model inputs -> first-stage activations (embeddings)
      blocks: homogeneous list of Layers (the pipelined trunk)
      post:   Layer or None applied after the last stage (final norm)
      head_call(post_params, pre_params, h, labels) -> loss array
              (defaults to post -> loss_fn; GPT supplies the tied-embedding
              projection here)
    """

    def __init__(self, pre, blocks, post=None, head_call=None):
        self.pre = pre
        self.blocks = list(blocks)
        self.post = post
        self.head_call = head_call


def resolve_parts(model, loss_fn):
    """PipelineParts for `model`: model.pipeline_parts(loss_fn) if it defines
    one, else the GPTForPretraining shape (embeddings/blocks/ln_f + tied
    head), else an actionable error."""
    if hasattr(model, "pipeline_parts"):
        return model.pipeline_parts(loss_fn)
    gpt = getattr(model, "gpt", None)
    if gpt is not None and hasattr(gpt, "blocks"):
        ln_f = gpt.ln_f

        def head_call(post_p, pre_p, h, labels):
            out, _ = ln_f.functional_call(post_p, {}, Tensor(h))
            w_emb = pre_p["word_embeddings.weight"]
            logits = jnp.einsum("bsh,vh->bsv", out._data, w_emb,
                                preferred_element_type=jnp.float32)
            l = loss_fn(Tensor(logits), Tensor(labels))
            return l._data if isinstance(l, Tensor) else l

        return PipelineParts(gpt.embeddings, list(gpt.blocks), gpt.ln_f,
                             head_call)
    raise ValueError(
        "cannot infer pipeline stages: give the model a "
        "pipeline_parts(loss_fn) -> PipelineParts method, or pass "
        "parts= explicitly (pre/blocks/post/head_call)")


# --------------------------------------------------------------------------
# core schedule
# --------------------------------------------------------------------------

def pipeline_apply(block_call, blocks_p, x_micro, num_stages, mesh=None,
                   pp_axis=None, dp_axis=None, remat=True, key=None):
    """Run the GPipe schedule.

    block_call(layer_params, x, key) -> x : ONE block (not a stage chunk);
    `key` is a fresh per-(tick, stage, layer) PRNG key for dropout.
    blocks_p: dict of [S, Lps, ...] stacked arrays.
    x_micro:  [M, mb, ...] microbatched first-stage input activations.
    Returns [M, mb, ...] last-stage output activations.
    """
    mesh = mesh or mesh_mod.get_mesh()
    pp_axis = pp_axis or mesh_mod.PP_AXIS
    if dp_axis is None and mesh is not None:
        dp_axis = (mesh_mod.DP_AXIS
                   if mesh_mod.DP_AXIS in mesh.axis_names else None)
    S = num_stages
    if key is None:
        key = jax.random.PRNGKey(0)

    body = jax.checkpoint(block_call) if remat else block_call

    def stage_fn(stage_params, x, stage_key):
        lps = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        def layer_body(h, xs):
            layer_params, k = xs
            return body(layer_params, h, k), None
        x, _ = lax.scan(layer_body, x,
                        (stage_params, jax.random.split(stage_key, lps)))
        return x

    act_spec = [None] * (x_micro.ndim - 1)
    act_spec[0] = dp_axis
    buf_sharding = (NamedSharding(mesh, P(pp_axis, *act_spec))
                    if mesh is not None else None)

    def constrain(buf):
        if buf_sharding is not None:
            return lax.with_sharding_constraint(buf, buf_sharding)
        return buf

    # pad the injection stream with S-1 bubble ticks
    pad = jnp.zeros((S - 1,) + x_micro.shape[1:], x_micro.dtype)
    stream = jnp.concatenate([x_micro, pad], axis=0)
    T = stream.shape[0]

    state_buf = constrain(jnp.zeros((S,) + x_micro.shape[1:], x_micro.dtype))

    def tick(buf, xs):
        x_t, k_t = xs
        buf = buf.at[0].set(x_t)
        buf = constrain(jax.vmap(stage_fn)(blocks_p, buf,
                                           jax.random.split(k_t, S)))
        y = buf[S - 1]
        buf = constrain(jnp.roll(buf, 1, axis=0))
        return buf, y

    _, ys = lax.scan(tick, state_buf, (stream, jax.random.split(key, T)))
    return ys[S - 1:]                                     # [M, mb, ...]


# --------------------------------------------------------------------------
# full train step for block-homogeneous LMs (GPT-style)
# --------------------------------------------------------------------------

class PipelineTrainStep:
    """Compiled pp(+dp+mp) training step for a model shaped like
    GPTForPretraining: embeddings -> homogeneous blocks -> final norm ->
    (tied) LM head. The analog of fleet PipelineOptimizer.minimize +
    PipelineTrainer/SectionWorker, as one jit.

    Usage:
        make_mesh({'dp': 2, 'pp': 4})
        step = PipelineTrainStep(model, gpt_pretrain_loss, opt, num_micro=8)
        loss = step(input_ids, labels)        # global batch
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None, num_micro=4,
                 num_stages=None, remat=True, donate=True, parts=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or mesh_mod.get_mesh() or mesh_mod.default_mesh()
        pp = mesh_mod.PP_AXIS
        assert pp in self.mesh.axis_names, "mesh needs a 'pp' axis"
        self.num_stages = num_stages or int(self.mesh.shape[pp])
        self.num_micro = num_micro
        self.dp_axis = (mesh_mod.DP_AXIS
                        if mesh_mod.DP_AXIS in self.mesh.axis_names else None)

        self.parts = parts or resolve_parts(model, loss_fn)
        blocks = self.parts.blocks
        L = len(blocks)
        S = self.num_stages
        assert L % S == 0, f"{L} layers not divisible by {S} stages"
        self.lps = L // S

        # ---- split state: pre (embeddings), blocks (stacked), post (norm)
        self.blocks_layer = blocks[0]
        stacked = {n: a.reshape((S, self.lps) + a.shape[1:])
                   for n, a in stack_block_params(blocks).items()}
        pre_p = {n: p._data
                 for n, p in self.parts.pre.named_parameters()}
        post_p = ({n: p._data
                   for n, p in self.parts.post.named_parameters()}
                  if self.parts.post is not None else {})

        params = {}
        params.update({"pre." + n: a for n, a in pre_p.items()})
        params.update({"blocks." + n: a for n, a in stacked.items()})
        params.update({"post." + n: a for n, a in post_p.items()})

        # ---- shardings
        hints = {n: getattr(p, "sharding", None)
                 for n, p in self.blocks_layer.named_parameters()}
        emb_hints = {n: getattr(p, "sharding", None)
                     for n, p in self.parts.pre.named_parameters()}
        self.param_specs = {}
        for n, a in params.items():
            if n.startswith("blocks."):
                self.param_specs[n] = _stacked_spec(
                    hints[n[len("blocks."):]], self.mesh, a.shape, pp)
            elif n.startswith("pre."):
                h = emb_hints.get(n[len("pre."):])
                self.param_specs[n] = _valid_spec(h, self.mesh, a.shape)
            else:
                self.param_specs[n] = P()

        opt_state = optimizer.init_opt_state(params)
        self.opt_specs = {n: {sn: self.param_specs[n] for sn in slots}
                          for n, slots in opt_state.items()}

        ns = lambda spec: NamedSharding(self.mesh, spec)
        shard = lambda a, spec: jax.device_put(a, ns(spec))
        self.params = {n: shard(a, self.param_specs[n])
                       for n, a in params.items()}
        self.opt_state = jax.tree_util.tree_map_with_path(
            lambda kp, a: shard(a, self.opt_specs[kp[0].key][kp[1].key]),
            opt_state)
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()

        embeddings = self.parts.pre
        mesh = self.mesh

        def block_call(layer_params, x, key):
            with state.functional_rng_ctx(key):
                out, _ = self.blocks_layer.functional_call(layer_params, {},
                                                           Tensor(x))
            return out._data if isinstance(out, Tensor) else out

        def pre_call(pre_p, ids, key):
            with state.functional_rng_ctx(key):
                out, _ = embeddings.functional_call(pre_p, {}, Tensor(ids))
            return out._data if isinstance(out, Tensor) else out

        if self.parts.head_call is not None:
            head_call = self.parts.head_call
        else:
            post_layer = self.parts.post

            def head_call(post_p, pre_p, h, labels):
                if post_layer is not None:
                    out, _ = post_layer.functional_call(post_p, {}, Tensor(h))
                    h = out._data if isinstance(out, Tensor) else out
                l = loss_fn(Tensor(h), Tensor(labels))
                return l._data if isinstance(l, Tensor) else l

        M = self.num_micro

        def _forward(p, key, ids_micro, labels_micro):
            pre = {n[4:]: a for n, a in p.items() if n.startswith("pre.")}
            blocks_p = {n[7:]: a for n, a in p.items()
                        if n.startswith("blocks.")}
            post = {n[5:]: a for n, a in p.items() if n.startswith("post.")}
            k_pre, k_pipe = jax.random.split(key)
            x = jax.vmap(lambda i, k: pre_call(pre, i, k))(
                ids_micro, jax.random.split(k_pre, M))
            hs = pipeline_apply(block_call, blocks_p, x, S, mesh=mesh,
                                remat=remat, key=k_pipe)
            losses = jax.vmap(
                lambda h, lab: head_call(post, pre, h, lab))(
                    hs, labels_micro)
            return jnp.mean(losses)

        def _step(params, opt_state, key, lr, step_i, ids, labels):
            loss, grads = jax.value_and_grad(
                lambda p: _forward(p, key, ids, labels))(params)
            new_params, new_opt = apply_fn(params, grads, opt_state, lr,
                                           step_i)
            return loss, new_params, new_opt

        param_sh = {n: ns(s) for n, s in self.param_specs.items()}
        opt_sh = {n: {sn: ns(s) for sn, s in slots.items()}
                  for n, slots in self.opt_specs.items()}
        data_spec = P(None, self.dp_axis) if self.dp_axis else P()
        self._data_sharding = ns(data_spec)
        self._compiled = jax.jit(
            _step,
            in_shardings=(param_sh, opt_sh, None, None, None,
                          self._data_sharding, self._data_sharding),
            out_shardings=(ns(P()), param_sh, opt_sh),
            donate_argnums=(0, 1) if donate else (),
        )

    # ------------------------------------------------------------------ step
    def _microbatch(self, a):
        a = a._data if isinstance(a, Tensor) else jnp.asarray(a)
        b = a.shape[0]
        M = self.num_micro
        assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
        a = a.reshape((M, b // M) + a.shape[1:])
        return jax.device_put(a, self._data_sharding)

    def __call__(self, input_ids, labels):
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        with self.mesh:
            loss, self.params, self.opt_state = self._compiled(
                self.params, self.opt_state, state.next_rng_key(), lr,
                jnp.asarray(self._step_i, jnp.int32),
                self._microbatch(input_ids), self._microbatch(labels))
        return Tensor(loss)

    def sync(self):
        """Write trained arrays back into the Layer tree (host)."""
        S, lps = self.num_stages, self.lps
        named = {}
        named.update({"pre." + n: p for n, p in
                      self.parts.pre.named_parameters()})
        if self.parts.post is not None:
            named.update({"post." + n: p for n, p in
                          self.parts.post.named_parameters()})
        stacked = {}
        for n, arr in self.params.items():
            if n.startswith("blocks."):
                a = jax.device_get(arr)
                stacked[n[len("blocks."):]] = a.reshape((S * lps,)
                                                        + a.shape[2:])
            else:
                named[n]._data = jnp.copy(jax.device_get(arr))
        unstack_block_params(self.parts.blocks, stacked)
        self.optimizer._global_step = self._step_i
