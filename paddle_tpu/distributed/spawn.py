"""paddle.distributed.spawn analog (ref python/paddle/distributed/spawn.py:276).

On TPU, one process drives all local chips (SPMD single-controller), so spawn
degenerates to running `func` once in-process for nprocs<=1; multi-host spawn
forks python processes with PADDLE_* env set, mirroring the reference's
launcher contract (used by localhost multi-process tests).
"""
import multiprocessing as mp
import os
import sys
import traceback


class _SpawnContext:
    def __init__(self, procs, error_queues):
        self.processes = procs
        self.error_queues = error_queues

    def join(self, timeout=None):
        for i, p in enumerate(self.processes):
            p.join(timeout)
            if p.exitcode not in (0, None):
                eq = self.error_queues[i]
                msg = eq.get() if not eq.empty() else f"exitcode {p.exitcode}"
                raise RuntimeError(f"spawned rank {i} failed:\n{msg}")
        return True


def _worker(func, rank, nprocs, args, error_queue, env):
    try:
        os.environ.update(env)
        func(*args)
    except Exception:
        error_queue.put(traceback.format_exc())
        sys.exit(1)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs <= 1:
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    procs, eqs = [], []
    base_port = int(options.get("started_port", 36701))
    endpoints = ",".join(f"127.0.0.1:{base_port + i}" for i in range(nprocs))
    for rank in range(nprocs):
        eq = ctx.SimpleQueue()
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{base_port + rank}",
        }
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, args, eq, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
        eqs.append(eq)
    context = _SpawnContext(procs, eqs)
    if join:
        context.join()
        return None
    return context
