"""Deep Gradient Compression (ref python/paddle/distributed/fleet/
meta_optimizers/dgc_optimizer.py DGCMomentumOptimizer +
paddle/fluid/framework/details/sparse_all_reduce_op_handle.cc +
paddle/fluid/operators/dgc_op.h).

DGC semantics, TPU-native: each dp replica keeps momentum-corrected
accumulators (U = m*U + g, V = V + U — the paper's momentum correction),
communicates only the top-(1-sparsity) fraction of |V| per parameter each
step, and zeroes the communicated entries locally (residual accumulation).
Parameters stay replica-identical: the update applies the cross-replica MEAN
of the sparse tensors with plain SGD (the paper's server-side apply).

Communication note (the honest TPU story): the reference's bandwidth win
comes from a custom sparse allreduce over commodity ethernet
(sparse_all_reduce_op_handle.cc). XLA exposes dense collectives only, so
here the sparse tensor is psum'd dense over ICI — DGC's *convergence*
semantics (what the sparsity does to training) are exact, while its *wire*
format is moot on ICI, whose bandwidth makes dense dp allreduce a non-issue
at the scales the reference targets. If DCN-scale sparse collectives become
available in XLA, only `_communicate` below changes.

Selection: per-parameter top-k on |V| (k static per compile from the
sparsity schedule), matching dgc_op.h's per-tensor threshold; ties admit a
few extra elements, exactly like the reference's sampled threshold.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import state
from ..framework.tensor import Tensor
from ..jit import _unwrap, _wrap
from . import mesh as mesh_mod


def _topk_mask(v, keep):
    """Boolean mask of the `keep` largest-|v| entries (per tensor)."""
    flat = jnp.abs(v).ravel()
    if keep >= flat.size:
        return jnp.ones_like(v, dtype=bool)
    thr = jax.lax.top_k(flat, keep)[0][-1]
    return jnp.abs(v) >= thr


class DGCTrainStep:
    """Compiled DGC training step over the 'dp' mesh axis.

    optimizer must be Momentum-flavored (the reference's
    DGCMomentumOptimizer subclasses Momentum): its lr and momentum drive the
    update; its own accumulators are bypassed — DGC's U/V replace them.

    sparsity: fraction of entries NOT communicated each step (e.g. 0.999
    keeps the top 0.1%). rampup_begin_step delays compression (dense warmup,
    like the reference's rampup_begin_step).
    """

    def __init__(self, model, loss_fn, optimizer, sparsity=0.999,
                 rampup_begin_step=0, mesh=None, dp_axis=None, donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or mesh_mod.get_mesh() or mesh_mod.default_mesh()
        self.dp_axis = dp_axis or (
            mesh_mod.DP_AXIS if mesh_mod.DP_AXIS in self.mesh.axis_names
            else self.mesh.axis_names[0])
        self.dp = int(self.mesh.shape[self.dp_axis])
        dp = self.dp
        momentum = float(getattr(optimizer, "_momentum", 0.9))
        self.sparsity = float(sparsity)
        self.rampup_begin_step = int(rampup_begin_step)

        params, buffers = model.functional_state()
        rep_axis = NamedSharding(self.mesh, P(self.dp_axis))
        replicated = NamedSharding(self.mesh, P())

        def stack(a):
            return jax.device_put(
                jnp.broadcast_to(jnp.zeros_like(a)[None], (dp,) + a.shape),
                rep_axis)

        self.params = {n: jax.device_put(a, replicated)
                       for n, a in params.items()}
        self.buffers = {n: jax.device_put(a, replicated)
                        for n, a in buffers.items()}
        self.U = {n: stack(a) for n, a in params.items()}   # momentum accum
        self.V = {n: stack(a) for n, a in params.items()}   # residual accum
        self._step_i = optimizer._global_step
        keep_frac = max(1e-6, 1.0 - self.sparsity)
        keep = {n: max(1, int(math.ceil(keep_frac * int(np.prod(a.shape)))))
                for n, a in params.items()}
        # sparsity 0 keeps everything: compression is the identity, so stay
        # on the dense (plain momentum) branch forever
        rampup = self.rampup_begin_step if keep_frac < 1.0 else 2 ** 30

        def _forward(p, b, key, x, y):
            with state.functional_rng_ctx(key):
                # loss may read model params directly (CRF transitions,
                # tied heads): keep the traced substitution alive through it
                # (same fix as jit.TrainStep._forward)
                with model._use_state(p, b):
                    out, _ = model.functional_call(p, b, *_wrap(x))
                    outs = out if isinstance(out, tuple) else (out,)
                    loss_t = loss_fn(*outs, *_wrap(y))
            return _unwrap(loss_t)

        def _one_replica_grads(p, b, key, x, y):
            return jax.value_and_grad(
                lambda pp: _forward(pp, b, key, x, y))(p)

        def _step(params, buffers, U, V, keys, lr, step_i, inputs, labels):
            # per-replica grads on the local micro-batch (params replicated)
            loss, grads = jax.vmap(
                _one_replica_grads,
                in_axes=(None, None, 0, 0, 0))(params, buffers, keys,
                                               inputs, labels)

            new_params, new_U, new_V = {}, {}, {}
            for n, p in params.items():
                g = grads[n]                       # [dp, ...]
                u = momentum * U[n] + g            # momentum correction
                v = V[n] + u                       # residual accumulation

                def compress(args):
                    u_, v_ = args
                    mask = jax.vmap(lambda vv: _topk_mask(vv, keep[n]))(v_)
                    sparse = jnp.where(mask, v_, 0)
                    return (jnp.where(mask, 0, u_),   # factor masking
                            jnp.where(mask, 0, v_), sparse)

                def dense(args):
                    # warmup (and sparsity=0): plain momentum SGD — U is the
                    # live momentum buffer, V stays empty, the whole
                    # momentum-corrected gradient is communicated (matching
                    # the reference, which runs the vanilla momentum op
                    # before rampup_begin_step)
                    u_, v_ = args
                    return (u_, jnp.zeros_like(v_), u_)

                u, v, sparse = jax.lax.cond(step_i > rampup, compress,
                                            dense, (u, v))
                comm = jnp.mean(sparse, axis=0)    # the (dense) allreduce
                new_params[n] = p - lr.astype(p.dtype) * comm.astype(p.dtype)
                new_U[n] = u
                new_V[n] = v
            return jnp.mean(loss), new_params, new_U, new_V

        sh_p = {n: replicated for n in self.params}
        sh_acc = {n: rep_axis for n in self.params}
        self._compiled = jax.jit(
            _step,
            in_shardings=(sh_p, {n: replicated for n in self.buffers},
                          sh_acc, sh_acc, rep_axis, None, None, None, None),
            out_shardings=(replicated, sh_p, sh_acc, sh_acc),
            donate_argnums=(0, 2, 3) if donate else (),
        )

    def _split_batch(self, arrs):
        rep = NamedSharding(self.mesh, P(self.dp_axis))
        out = []
        for a in arrs:
            a = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            if a.shape[0] % self.dp != 0:
                raise ValueError(
                    f"DGC batch dim {a.shape[0]} must be divisible by "
                    f"dp={self.dp}")
            out.append(jax.device_put(
                a.reshape((self.dp, a.shape[0] // self.dp) + a.shape[1:]),
                rep))
        return tuple(out)

    def __call__(self, inputs, labels):
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        keys = jax.random.split(state.next_rng_key(), self.dp)
        with self.mesh:
            loss, self.params, self.U, self.V = self._compiled(
                self.params, self.buffers, self.U, self.V, keys, lr,
                jnp.asarray(self._step_i, jnp.int32),
                self._split_batch(inputs), self._split_batch(labels))
        return Tensor(loss)

    def sync(self):
        named_p = dict(self.model.named_parameters())
        for n, arr in self.params.items():
            named_p[n]._data = jnp.copy(jax.device_get(arr))
        self.optimizer._global_step = self._step_i
