"""Ulysses-style sequence parallelism — all-to-all head redistribution.

Second sequence-parallel strategy next to ring attention (PAPERS.md
DeepSpeed-Ulysses, arXiv:2309.14509); NEW capability relative to the
reference (SURVEY.md §5: Yelrose/Paddle has no sequence parallelism).

Where ring attention streams K/V shards around the ICI ring (constant
memory, n ppermute hops), Ulysses swaps WHICH dim is sharded: activations
arrive sequence-sharded [B, H, S/n, D], one all-to-all re-shards them to
head-sharded [B, H/n, S, D], each device runs ordinary (flash) attention
on its full-sequence head slice, and a second all-to-all restores
sequence sharding. Two collectives per call instead of n, at the price of
holding S x (H/n) activations; the right trade when heads >= sp and the
sequence shard still fits HBM. Composes with 'dp' (batch) like the ring.

Both strategies expose the same call contract, so GPTAttention can pick
per-config (sequence_parallel="ring" | "ulysses").
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod


def ulysses_attention_shard(q, k, v, *, axis_name, causal, scale):
    """Per-shard body (inside shard_map). q/k/v local: [B, H, S/n, D].
    Requires H % n == 0 (head-parallel redistribution)."""
    n = lax.axis_size(axis_name)
    b, h, s_loc, d = q.shape
    if h % n != 0:
        raise ValueError(f"num_heads {h} not divisible by sp={n}")

    def seq_to_head(x):
        # [B, H, S/n, D] -> [B, H/n, S, D]: split heads across devices,
        # concatenate sequence. all_to_all splits axis 1, concats axis 2.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh = seq_to_head(q)
    kh = seq_to_head(k)
    vh = seq_to_head(v)
    from ..ops.pallas.flash_attention import _flash_array
    oh = _flash_array(qh, kh, vh, causal=causal, scale=scale)
    return head_to_seq(oh)


def ulysses_attention(q, k, v, causal=False, scale=None,
                      axis_name=mesh_mod.SP_AXIS, mesh=None):
    """Array-level Ulysses attention over globally-shaped [B,H,S,D] arrays.
    Falls back to single-device flash attention when the mesh has no (or a
    trivial) 'sp' axis. Mirrors ring_attention's sharding contract."""
    mesh = mesh or mesh_mod.get_mesh()
    if (mesh is None or axis_name not in mesh.axis_names
            or int(mesh.shape[axis_name]) == 1):
        from ..ops.pallas.flash_attention import _flash_array
        return _flash_array(q, k, v, causal=causal, scale=scale)
    n = int(mesh.shape[axis_name])
    if q.shape[-2] % n != 0:
        raise ValueError(f"sequence length {q.shape[-2]} not divisible by "
                         f"sp={n}")
    if q.shape[1] % n != 0:
        raise ValueError(f"num_heads {q.shape[1]} not divisible by sp={n} "
                         "(use ring attention for head counts below the "
                         "sp degree)")
    batch_axis = mesh_mod.DP_AXIS if (
        mesh_mod.DP_AXIS in mesh.axis_names
        and q.shape[0] % int(mesh.shape[mesh_mod.DP_AXIS]) == 0) else None
    spec = P(batch_axis, None, axis_name, None)
    f = jax.shard_map(
        functools.partial(ulysses_attention_shard, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)


def ulysses_flash_attention(q, k, v, causal=False, scale=None,
                            axis_name=mesh_mod.SP_AXIS, mesh=None):
    """Tensor-level op (tape/functional integrated via the dispatcher)."""
    from ..ops.dispatch import apply

    def fn(q_, k_, v_):
        return ulysses_attention(q_, k_, v_, causal=causal, scale=scale,
                                 axis_name=axis_name, mesh=mesh)

    return apply(fn, (q, k, v), name="ulysses_flash_attention")
