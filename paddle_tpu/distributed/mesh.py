"""Mesh/axis registry — the TPU-native `NCCLCommContext` (ref
paddle/fluid/platform/collective_helper.h:65: ring_id -> comm registry).

The reference keys communicators by integer ring_id; XLA keys collectives by
*named mesh axes*. This registry maps both worlds: groups/ring_ids resolve to
(mesh, axis-name) pairs so c_allreduce(ring_id=k) lowers to lax.psum over the
right axis. Axis naming convention across the framework:
  'dp' data parallel | 'mp' tensor/model parallel | 'pp' pipeline stages |
  'sp' sequence/context parallel | 'ep' expert parallel
"""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

_current_mesh = None
_groups = {}          # group id -> _Group
_next_group_id = 1

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"
EP_AXIS = "ep"


class _Group:
    def __init__(self, gid, axis_name, ranks=None):
        self.id = gid
        self.axis_name = axis_name
        self.ranks = ranks

    @property
    def nranks(self):
        if self.ranks:
            return len(self.ranks)
        m = get_mesh()
        return int(m.shape[self.axis_name]) if m is not None else 1


def default_mesh():
    """1-D data-parallel mesh over all devices (the DP allreduce ring analog)."""
    global _current_mesh
    if _current_mesh is None:
        devs = np.asarray(jax.devices())
        _current_mesh = Mesh(devs, (DP_AXIS,))
        _groups[0] = _Group(0, DP_AXIS)
    return _current_mesh


def make_mesh(shape_dict):
    """Build + install an N-D mesh, e.g. {'dp': 2, 'mp': 4}."""
    global _current_mesh
    names = tuple(shape_dict.keys())
    sizes = tuple(int(v) for v in shape_dict.values())
    n = int(np.prod(sizes))
    devs = np.asarray(jax.devices()[:n]).reshape(sizes)
    _current_mesh = Mesh(devs, names)
    _groups.clear()
    _groups[0] = _Group(0, names[0])
    return _current_mesh


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh():
    return _current_mesh


def mesh_axes():
    m = get_mesh()
    return tuple(m.axis_names) if m is not None else ()


def register_group(axis_name, ranks=None):
    """ring_id/new_group analog: returns a group handle bound to a mesh axis."""
    global _next_group_id
    gid = _next_group_id
    _next_group_id += 1
    g = _Group(gid, axis_name, ranks)
    _groups[gid] = g
    return g


def get_group(group=None):
    if group is None or group == 0:
        default_mesh()
        return _groups[0]
    if isinstance(group, _Group):
        return group
    return _groups[int(group)]


class MeshContext:
    """Context manager installing a mesh (for `with MeshContext({'dp':8}):`)."""

    def __init__(self, shape_dict_or_mesh):
        if isinstance(shape_dict_or_mesh, Mesh):
            self.mesh = shape_dict_or_mesh
        else:
            names = tuple(shape_dict_or_mesh.keys())
            sizes = tuple(int(v) for v in shape_dict_or_mesh.values())
            n = int(np.prod(sizes))
            devs = np.asarray(jax.devices()[:n]).reshape(sizes)
            self.mesh = Mesh(devs, names)
        self._saved = None

    def __enter__(self):
        global _current_mesh
        self._saved = _current_mesh
        _current_mesh = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._saved
        return False
