"""paddle.distributed.utils compat surface (ref
python/paddle/distributed/utils.py launch helpers). Thin functional
equivalents over this package's launcher machinery (distributed/launch.py
Cluster/Pod model) — external tooling that scripts against the reference's
helper names keeps working."""
import logging
import socket

from .launch import get_cluster, Pod  # noqa: F401  (re-exported helpers)


def find_free_ports(num):
    """ref utils.py find_free_ports: grab `num` kernel-assigned ports."""
    socks, ports = [], []
    try:
        for _ in range(num):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return set(ports)


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(levelname)s %(asctime)s %(filename)s:%(lineno)d] %(message)s"))
        logger.addHandler(h)
    return logger


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """ref utils.py add_arguments (fluid-era argparse helper)."""
    type = (lambda v: v.lower() in ("true", "1")) if type == bool else type
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + " Default: %(default)s.", **kwargs)
