"""paddle_tpu.distributed (ref python/paddle/distributed).

TPU-native mapping (SURVEY.md §5): the reference's ring_id->NCCL-comm registry
becomes a named-axis Mesh registry; c_* collective ops become lax collectives
resolved inside shard_map/pjit; rendezvous is jax.distributed's coordination
service instead of a TCP ncclUniqueId bootstrap.
"""
from .env import (init_parallel_env, get_rank, get_world_size, ParallelEnv,
                  is_initialized)
from .mesh import (MeshContext, get_mesh, set_mesh, mesh_axes, default_mesh)
from .collective import (all_reduce, all_gather, broadcast, reduce, scatter,
                         barrier, send, recv, split, ReduceOp, new_group,
                         wait, reduce_scatter, alltoall)
from .parallel import DataParallel
from .ring_attention import ring_attention, ring_flash_attention
from . import fleet
from .spawn import spawn
from . import utils
from .utils import (find_free_ports, get_host_name_ip, get_logger,
                    get_cluster, add_arguments)
