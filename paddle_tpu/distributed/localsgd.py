"""LocalSGD — k local optimizer steps per replica, then parameter averaging.

TPU-native redesign of the reference LocalSGD meta-optimizer
(ref python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py:
skip per-step grad allreduce, every k steps c_allreduce_sum params / nranks):
under GSPMD you cannot "skip the allreduce" — the partitioner inserts it
wherever replicated params meet dp-sharded batches. Instead each replica's
divergent weights are made EXPLICIT: params/opt-state carry a leading
replica axis of size dp, sharded P('dp') over the mesh. Per-device memory
equals plain replication (each device holds exactly one replica), but the
vmapped step lets every replica march independently — zero cross-replica
communication on local steps. Every k-th step the params are averaged over
the replica axis (ONE all-reduce over 'dp' riding ICI) and re-broadcast,
all inside the same compiled step via lax.cond.

Optimizer moments stay local (matching the reference, which averages only
the parameters); buffers (BN stats) also stay local between syncs.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import state
from ..framework.tensor import Tensor
from ..jit import _unwrap, _wrap
from . import mesh as mesh_mod


class LocalSGDTrainStep:
    """Compiled LocalSGD step over the 'dp' axis of the current Mesh.

    Usage:
        make_mesh({'dp': 8})
        step = LocalSGDTrainStep(model, loss_fn, opt, k_steps=4)
        loss = step(batch_inputs, batch_labels)   # global batch arrays
    """

    def __init__(self, model, loss_fn, optimizer, k_steps=1, mesh=None,
                 dp_axis=None, donate=True, adaptive=False,
                 init_k_steps=1, begin_step=1):
        from ..jit import transforms as tfm
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.k_steps = max(1, int(k_steps))
        self.mesh = mesh or mesh_mod.get_mesh() or mesh_mod.default_mesh()
        self.dp_axis = dp_axis or (
            mesh_mod.DP_AXIS if mesh_mod.DP_AXIS in self.mesh.axis_names
            else self.mesh.axis_names[0])
        self.dp = self.mesh.shape[self.dp_axis]
        dp = self.dp

        params, buffers = model.functional_state()
        rep = NamedSharding(self.mesh, P(self.dp_axis))

        def stack(a):
            return jax.device_put(
                jnp.broadcast_to(a[None], (dp,) + a.shape), rep)

        self.params = {n: stack(a) for n, a in params.items()}
        self.buffers = {n: stack(a) for n, a in buffers.items()}
        self.opt_state = jax.tree.map(stack,
                                      optimizer.init_opt_state(params))
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()

        # strategy transforms: amp/recompute apply per replica; k-step
        # accumulation is inherent to LocalSGD (its local steps), so a
        # gradient_merge flag is rejected rather than silently ignored
        self.transforms = tfm.resolve(optimizer)
        if tfm.merge_config(self.transforms)[0] > 1:
            raise ValueError(
                "strategy.gradient_merge cannot be combined with localsgd "
                "(local steps already accumulate); raise localsgd k_steps "
                "instead")

        def _forward(p, b, key, x, y):
            with state.functional_rng_ctx(key):
                # loss may read model params directly (CRF transitions,
                # tied heads): keep the traced substitution alive through it
                # (same fix as jit.TrainStep._forward)
                with model._use_state(p, b):
                    out, new_b = model.functional_call(p, b, *_wrap(x))
                    outs = out if isinstance(out, tuple) else (out,)
                    loss_t = loss_fn(*outs, *_wrap(y))
            return _unwrap(loss_t), new_b

        _forward = tfm.wrap_forward(_forward, self.transforms)

        def _one_replica(p, b, o, key, lr, step_i, x, y):
            (loss, new_b), grads = jax.value_and_grad(
                lambda pp: _forward(pp, b, key, x, y), has_aux=True)(p)
            new_p, new_o = apply_fn(p, grads, o, lr, step_i)
            return loss, new_p, new_b, new_o

        def _step(params, buffers, opt_state, keys, lr, step_i, do_sync,
                  inputs, labels):
            loss, new_p, new_b, new_o = jax.vmap(
                _one_replica,
                in_axes=(0, 0, 0, 0, None, None, 0, 0))(
                params, buffers, opt_state, keys, lr, step_i, inputs,
                labels)

            def sync(p):
                # ONE collective: mean over the replica axis, re-broadcast
                return jax.tree.map(
                    lambda a: jnp.broadcast_to(
                        jnp.mean(a, axis=0, keepdims=True), a.shape), p)

            # the sync decision is a runtime input: fixed-k mode passes
            # step_i % k == 0; adaptive mode (ref AdaptiveLocalSGD) lets
            # the host controller grow/shrink the interval from the loss
            new_p = jax.lax.cond(do_sync, sync, lambda p: p, new_p)
            return jnp.mean(loss), new_p, new_b, new_o

        sh = {"params": {n: rep for n in self.params},
              "buffers": {n: rep for n in self.buffers},
              "opt": jax.tree.map(lambda _: rep, self.opt_state)}
        self._compiled = jax.jit(
            _step,
            in_shardings=(sh["params"], sh["buffers"], sh["opt"], rep,
                          None, None, None, None, None),
            out_shardings=(NamedSharding(self.mesh, P()), sh["params"],
                           sh["buffers"], sh["opt"]),
            donate_argnums=(0, 1, 2) if donate else (),
        )

        # adaptive interval controller state (ref AdaptiveLocalSGD:
        # next_k = clip(ceil(sqrt(lr_0*loss / (lr*loss_0) * init_k)), 1, 16),
        # recomputed at every sync from the replica-mean loss)
        self.adaptive = bool(adaptive)
        self.init_k_steps = max(1, int(init_k_steps))
        self.begin_step = max(1, int(begin_step))
        if self.adaptive:
            self.k_steps = self.init_k_steps
        self._last_sync = 0
        self._loss0 = None
        self._lr0 = None

    # ------------------------------------------------------------------ step
    def _split_batch(self, arrs):
        """Global batch [B, ...] -> per-replica [dp, B/dp, ...], sharded."""
        rep = NamedSharding(self.mesh, P(self.dp_axis))
        out = []
        for a in arrs:
            a = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            if a.shape[0] % self.dp != 0:
                raise ValueError(
                    f"LocalSGD batch dim {a.shape[0]} must be divisible "
                    f"by dp={self.dp}")
            out.append(jax.device_put(
                a.reshape((self.dp, a.shape[0] // self.dp) + a.shape[1:]),
                rep))
        return tuple(out)

    def __call__(self, inputs, labels):
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        self._step_i += 1
        lr = float(self.optimizer.get_lr())
        if self.adaptive:
            # ref AdaptiveLocalSGD warmup: sync EVERY step until
            # begin_step (dense-DP lockstep), then loss-driven intervals
            do_sync = (self._step_i < self.begin_step
                       or self._step_i - self._last_sync >= self.k_steps)
        else:
            do_sync = self._step_i % self.k_steps == 0
        keys = jax.random.split(state.next_rng_key(), self.dp)
        with self.mesh:
            loss, self.params, self.buffers, self.opt_state = \
                self._compiled(self.params, self.buffers, self.opt_state,
                               keys, jnp.asarray(lr, jnp.float32),
                               jnp.asarray(self._step_i, jnp.int32),
                               jnp.asarray(do_sync),
                               self._split_batch(inputs),
                               self._split_batch(labels))
        if self.adaptive and (do_sync or self._loss0 is None):
            # host round-trip for ONE scalar, and only on steps whose
            # loss the controller actually consumes — non-sync steps stay
            # fully async-dispatched
            lv = float(np.asarray(jax.device_get(loss)))
            if self._loss0 is None:
                self._loss0, self._lr0 = max(lv, 1e-12), max(lr, 1e-12)
            if do_sync:
                self._last_sync = self._step_i
                ratio = (self._lr0 * max(lv, 1e-12)) / (
                    max(lr, 1e-12) * self._loss0)
                self.k_steps = int(np.clip(
                    np.ceil(np.sqrt(ratio * self.init_k_steps)), 1, 16))
        return Tensor(loss)

    def sync(self):
        """Average replicas and write back into the live Layer/Optimizer."""
        named_p = dict(self.model.named_parameters())
        for n, arr in self.params.items():
            named_p[n]._data = jnp.asarray(
                np.asarray(jax.device_get(arr)).mean(0))
        named_b = dict(self.model.named_buffers())
        for n, arr in self.buffers.items():
            named_b[n]._data = jnp.asarray(
                np.asarray(jax.device_get(arr)).mean(0))
        self.optimizer._global_step = self._step_i
