"""Multi-process launcher: `python -m paddle_tpu.distributed.launch train.py`.

TPU-native redesign of the reference launcher
(ref python/paddle/distributed/fleet/launch.py:208,260,334 launch_collective,
launch_utils.py:57 Cluster/Pod/Trainer model, :435 TrainerProc watch loop):
same cluster model and per-rank env contract, but the per-rank env also
carries the JAX distributed-initialization variables so worker processes
rendezvous through the jax coordination service (the ncclUniqueId-TCP
bootstrap analog, ref platform/gen_comm_id_helper.cc:284 — here the
coordinator is jax.distributed's builtin service on rank 0).

Failure handling mirrors TrainerProc/watch_local_trainers: any dead worker
tears the pod down (ref launch_utils.py watch_local_trainers + the PS-mode
HeartBeatMonitor semantics, operators/distributed/heart_beat_monitor.h:51).

On a real pod each host runs its own slice of ranks; on one host this gives
the multi-process localhost harness the reference tests rely on
(SURVEY.md §4).
"""
import argparse
import os
import signal
import subprocess
import sys
import time


class Trainer:
    def __init__(self, rank, endpoint, devices):
        self.rank = rank
        self.endpoint = endpoint
        self.devices = devices


class Pod:
    """One host's worth of trainers (ref launch_utils.py:57 Cluster/Pod)."""

    def __init__(self, trainers, coordinator):
        self.trainers = trainers
        self.coordinator = coordinator


def get_cluster(nproc_per_node, start_port=36777, ips="127.0.0.1",
                nnodes=None):
    """nproc_per_node ranks on EACH host in --ips (total = per_node x
    hosts — the reference's launch contract). `nnodes` must match the host
    count when both are given; with a single host entry it replicates it
    (localhost multi-node simulation: --nnodes 2 gives two 'nodes' on
    127.0.0.1 with distinct port ranges, the way the reference simulates
    clusters multiprocess-on-localhost)."""
    hosts = [h for h in ips.split(",") if h]
    if nnodes and nnodes != len(hosts):
        if len(hosts) != 1:
            raise ValueError(
                f"--nnodes {nnodes} does not match --ips ({ips}, "
                f"{len(hosts)} hosts): give one ip (replicated) or exactly "
                f"nnodes ips")
        hosts = hosts * nnodes
    per_host = nproc_per_node
    trainers = []
    for hi, host in enumerate(hosts):
        for i in range(per_host):
            rank = hi * per_host + i
            # per-node port ranges so simulated nodes on one ip don't clash
            trainers.append(Trainer(
                rank, f"{host}:{start_port + hi * per_host + i}", [i]))
    return Pod(trainers, f"{hosts[0]}:{start_port - 1}")


def _local_addresses():
    """Addresses that mean 'this host' — POD_IP (the reference's per-host
    identity env, launch_utils.py get_cluster_from_args), hostname, and
    loopback."""
    import socket
    addrs = {"127.0.0.1", "localhost", "0.0.0.0"}
    pod_ip = os.environ.get("POD_IP")
    if pod_ip:
        addrs.add(pod_ip)
    try:
        hn = socket.gethostname()
        addrs.add(hn)
        addrs.add(socket.gethostbyname(hn))
    except OSError:
        pass
    return addrs


def local_trainers(pod):
    """This host's slice of the pod — only these ranks are spawned here
    (each host in --ips runs the launcher; ref launch_collective spawns
    procs for the local pod only)."""
    addrs = _local_addresses()
    mine = [t for t in pod.trainers if t.endpoint.split(":")[0] in addrs]
    if mine:
        return mine
    pod_hosts = {t.endpoint.split(":")[0] for t in pod.trainers}
    if len(pod_hosts) == 1:
        # single-host pod whose ip isn't a local alias (e.g. NAT): safe —
        # only one host will ever run this launcher
        return pod.trainers
    raise RuntimeError(
        f"cannot identify this host among pod hosts {sorted(pod_hosts)} "
        f"(local addresses: {sorted(addrs)}); set POD_IP to this host's "
        f"ip from --ips so each host spawns only its own ranks")


def _rank_env(pod, trainer, nproc, training_script_args):
    env = dict(os.environ)
    env.update({
        # reference contract (launch_utils.py:258 get_proc_env)
        "PADDLE_TRAINER_ID": str(trainer.rank),
        "PADDLE_CURRENT_ENDPOINT": trainer.endpoint,
        "PADDLE_TRAINERS_NUM": str(nproc),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(
            t.endpoint for t in pod.trainers),
        # jax coordination service (the TPU-native bootstrap)
        "COORDINATOR_ADDRESS": pod.coordinator,
        "PROCESS_ID": str(trainer.rank),
        "NUM_PROCESSES": str(nproc),
    })
    return env


def launch_procs(pod, script, script_args, nproc, log_dir=None,
                 max_restarts=0):
    """Start one process per trainer; monitor; on failure either restart the
    whole local pod (elastic mode, up to `max_restarts` times — ref
    paddle.distributed.elastic / launch_utils watch + respawn) or tear it
    down (ref launch_utils.py:435 TrainerProc + watch_local_trainers).

    Pod-level restart, not per-rank: a collective job cannot admit a lone
    rejoining rank mid-allreduce; the reference's elastic controller
    restarts the trainer group the same way."""
    mine = local_trainers(pod)
    attempts = 0
    while True:
        rc = _run_pod_once(pod, mine, script, script_args, nproc, log_dir,
                           attempt=attempts)
        if rc == 0 or attempts >= max_restarts:
            return rc
        attempts += 1
        sys.stderr.write(
            f"pod failed (exit {rc}); elastic restart "
            f"{attempts}/{max_restarts}\n")


def _run_pod_once(pod, mine, script, script_args, nproc, log_dir, attempt=0):
    procs = []
    logs = []
    # host-collective rendezvous (gloo analog, ref role_maker gloo HTTP
    # store): the rank-0 pod hosts a kv store on a DETERMINISTIC port
    # (coordinator port + 1) so every pod — including remote hosts whose
    # launcher can't receive env from ours — computes the same endpoint;
    # the store binds all interfaces for them. An externally provided
    # PADDLE_GLOO_HTTP_ENDPOINT (cluster scheduler) wins.
    kv = None
    kv_ep = os.environ.get("PADDLE_GLOO_HTTP_ENDPOINT")
    if kv_ep is None and pod.coordinator:
        host, cport = pod.coordinator.rsplit(":", 1)
        kv_ep = f"{host}:{int(cport) + 1}"
        if mine and mine[0].rank == 0:
            from .gloo import KVStore
            kv = KVStore(port=int(cport) + 1)
    for t in mine:
        env = _rank_env(pod, t, nproc, script_args)
        env["PADDLE_RESTART_ATTEMPT"] = str(attempt)
        if kv_ep:
            env["PADDLE_GLOO_HTTP_ENDPOINT"] = kv_ep
        cmd = [sys.executable, "-u", script] + list(script_args)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            suffix = f".r{attempt}" if attempt else ""
            f = open(os.path.join(log_dir,
                                  f"workerlog.{t.rank}{suffix}"), "w")
            logs.append(f)
            p = subprocess.Popen(cmd, env=env, stdout=f, stderr=f)
        else:
            p = subprocess.Popen(cmd, env=env)
        procs.append(p)
    try:
        alive = True
        ret = 0
        while alive:
            alive = False
            for i, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    # a worker died: tear down the pod (heart-beat analog)
                    sys.stderr.write(
                        f"trainer rank {mine[i].rank} failed "
                        f"(exit {rc}); aborting pod\n")
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    ret = rc
                    alive = False
                    break
            if alive:
                time.sleep(0.5)
        for p in procs:
            p.wait()
        return ret if ret else max(
            (p.returncode or 0 for p in procs), default=0)
    finally:
        for f in logs:
            f.close()
        if kv is not None:
            kv.stop()


def main(argv=None):
    parser = argparse.ArgumentParser(
        "paddle_tpu.distributed.launch",
        description="launch a distributed job: one process per device/rank")
    parser.add_argument("--nproc_per_node", type=int, default=None)
    parser.add_argument("--nnodes", type=int, default=None,
                        help="number of nodes; with a single --ips entry the "
                             "nodes are simulated on localhost (multi-host "
                             "smoke testing, ref launch.py --nnodes)")
    parser.add_argument("--ips", type=str, default="127.0.0.1",
                        help="comma-split host ips (ref launch.py --ips)")
    parser.add_argument("--start_port", type=int, default=36777)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--server_num", type=int, default=0,
                        help="PS mode: number of parameter servers")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="elastic: restart the local pod up to N times "
                             "on worker failure (ref distributed.elastic)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    nproc = args.nproc_per_node
    if nproc is None:
        try:
            import jax
            nproc = max(1, jax.local_device_count())
        except Exception:
            nproc = 1

    if args.server_num:
        return _launch_ps(args, nproc)

    pod = get_cluster(nproc, args.start_port, args.ips, nnodes=args.nnodes)
    total = len(pod.trainers)
    return launch_procs(pod, args.training_script,
                        args.training_script_args, total, args.log_dir,
                        max_restarts=args.max_restarts)


def _launch_ps(args, nproc):
    """PS mode: servers + workers with TRAINING_ROLE env
    (ref launch.py launch_ps)."""
    host = args.ips.split(",")[0]
    server_eps = ",".join(f"{host}:{args.start_port + i}"
                          for i in range(args.server_num))
    procs = []
    for role, count in (("PSERVER", args.server_num), ("TRAINER", nproc)):
        for i in range(count):
            env = dict(os.environ)
            env.update({
                "TRAINING_ROLE": role,
                "PADDLE_PSERVERS_IP_PORT_LIST": server_eps,
                "PADDLE_TRAINERS_NUM": str(nproc),
                "PADDLE_TRAINER_ID": str(i),
                "POD_IP": host,
                "PADDLE_PORT": str(args.start_port + i),
            })
            cmd = [sys.executable, "-u", args.training_script] + \
                list(args.training_script_args)
            procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs[args.server_num:]:   # wait for trainers
        rc = p.wait() or rc
    for p in procs[:args.server_num]:   # then stop servers
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
            p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
