"""Tensor-parallel layers (ref python/paddle/distributed/collective.py:492-620
_parallel_linear/_parallel_embedding — Megatron-style TP).

TPU-native: weights carry PartitionSpec sharding hints on the 'mp' axis; under
pjit, GSPMD propagates them and inserts the minimal collectives (AllReduce on
row-parallel outputs, AllGather when gather_output=True). When traced inside
shard_map (explicit-collective mode, used by the pipeline engine), the layers
issue lax collectives directly — both regimes are supported by checking for a
bound axis.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..ops.dispatch import apply
from . import mesh as mesh_mod


def _mp_size():
    m = mesh_mod.get_mesh()
    if m is not None and mesh_mod.MP_AXIS in m.axis_names:
        return int(m.shape[mesh_mod.MP_AXIS])
    return 1


def _axis_bound(name):
    """True while tracing inside shard_map with this axis in scope."""
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False
    except Exception:
        return False


class ColumnParallelLinear(Layer):
    """Y = X @ [W_1 | W_2 | ... | W_p]: weight column-sharded on 'mp'
    (ref collective.py _parallel_linear axis=1)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None
        if self.bias is not None:
            self.bias.sharding = P(mesh_mod.MP_AXIS)

    def forward(self, x):
        if _axis_bound(mesh_mod.MP_AXIS):
            # explicit mode: local shard matmul; output is mp-sharded on cols
            out = F.linear(x, self.weight, self.bias)
            if self.gather_output:
                arr = lax.all_gather(out._data, mesh_mod.MP_AXIS, axis=-1,
                                     tiled=True)
                out = Tensor(arr, stop_gradient=out.stop_gradient)
                out._node, out._slot = None, 0
            return out
        # GSPMD mode: full logical shapes; sharding constraint steers SPMD
        out = F.linear(x, self.weight, self.bias)
        return _with_sharding(out, P(None, mesh_mod.MP_AXIS)
                              if not self.gather_output else None)


class RowParallelLinear(Layer):
    """Y = sum_p X_p @ W_p: weight row-sharded, output AllReduced
    (ref collective.py _parallel_linear axis=0)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.sharding = P(mesh_mod.MP_AXIS, None)
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None

    def forward(self, x):
        if _axis_bound(mesh_mod.MP_AXIS):
            out = F.linear(x, self.weight, None)
            arr = lax.psum(out._data, mesh_mod.MP_AXIS)
            out = Tensor(arr, stop_gradient=out.stop_gradient)
            if self.bias is not None:
                out = out + self.bias
            return out
        out = F.linear(x, self.weight, None)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding row(vocab)-sharded on 'mp' with shard_index + masked lookup +
    psum (ref collective.py:566 _parallel_embedding)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, x):
        if _axis_bound(mesh_mod.MP_AXIS):
            mp = _mp_size()
            from ..ops.manipulation import shard_index
            rank = lax.axis_index(mesh_mod.MP_AXIS)
            shard_size = (self.num_embeddings + mp - 1) // mp

            def f(idx, w):
                local = idx - rank * shard_size
                valid = (local >= 0) & (local < w.shape[0])
                safe = jnp.where(valid, local, 0)
                out = jnp.take(w, safe, axis=0)
                out = jnp.where(valid[..., None], out, 0.0)
                return lax.psum(out, mesh_mod.MP_AXIS)
            return apply(f, (x, self.weight), name="vocab_parallel_embedding")
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE over mp-sharded logits."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, logits, label):
        def f(z, y):
            if _axis_bound(mesh_mod.MP_AXIS):
                mp_max = lax.pmax(jnp.max(z, axis=-1, keepdims=True),
                                  mesh_mod.MP_AXIS)
                e = jnp.exp(z - mp_max)
                denom = lax.psum(jnp.sum(e, axis=-1, keepdims=True),
                                 mesh_mod.MP_AXIS)
                rank = lax.axis_index(mesh_mod.MP_AXIS)
                vshard = z.shape[-1]
                local = y - rank * vshard
                valid = (local >= 0) & (local < vshard)
                safe = jnp.where(valid, local, 0)
                picked = jnp.take_along_axis(z - mp_max, safe[..., None],
                                             axis=-1)[..., 0]
                picked = jnp.where(valid, picked, 0.0)
                picked = lax.psum(picked, mesh_mod.MP_AXIS)
                return jnp.mean(jnp.log(denom[..., 0]) - picked)
            return jnp.mean(-jnp.take_along_axis(
                jax.nn.log_softmax(z, -1), y[..., None], axis=-1))
        return apply(f, (logits, label), name="parallel_cross_entropy")


def _with_sharding(t, spec):
    """Attach a GSPMD sharding constraint inside pjit traces."""
    if spec is None:
        return t
    a = t._data
    if isinstance(a, jax.core.Tracer):
        mesh = mesh_mod.get_mesh()
        if mesh is not None:
            try:
                a = jax.lax.with_sharding_constraint(
                    a, jax.sharding.NamedSharding(mesh, spec))
                out = Tensor(a, stop_gradient=t.stop_gradient)
                return out
            except (ValueError, RuntimeError):
                return t
    return t
