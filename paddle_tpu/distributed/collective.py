"""Collective API (ref python/paddle/distributed/collective.py:101-457 and the
c_* kernels in paddle/fluid/operators/collective/).

Semantics mapping (SURVEY.md §5 "Distributed communication backend"):
  c_allreduce_{sum,max,min,prod} -> lax.psum/pmax/pmin (inside SPMD traces)
  c_allgather                    -> lax.all_gather
  c_reducescatter                -> lax.psum_scatter
  c_broadcast                    -> broadcast from src via lax.all_gather pick
  send_v2/recv_v2 (p2p)          -> lax.ppermute (pipeline edges)
  c_sync_calc/comm_stream        -> no-ops (XLA async collectives are
                                    scheduler-ordered; wait() kept for API)

Two execution regimes:
  * traced (inside shard_map/pjit over a Mesh axis): lax collectives — the
    performance path, compiled onto ICI.
  * eager single-controller: arrays are process-local and replicated, so
    reductions over the "world" are identity; multi-process eager sync uses
    jax process-level primitives only where needed (barrier).
These match the reference's dual dygraph/static collective paths.
"""
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import as_array
from ..utils import chaos, telemetry, profiler, \
    flight_recorder as _flight_recorder
from . import mesh as mesh_mod


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


def _axis(group):
    return mesh_mod.get_group(group).axis_name


# --------------------------------------------------------------- telemetry
# Byte/call accounting per op+group, RecordEvent spans (so communication
# shows up next to compute in the chrome trace), and journal `collective`
# events through the current flight recorder. Traced call sites (inside
# shard_map/pjit) run ONCE PER TRACE, so there the counters measure the
# communication the compiled program issues per executable, not per step
# — docs/observability.md spells this out.

_COLLECTIVE_CALLS = telemetry.counter(
    "collective_calls_total",
    "Collective op invocations (traced call sites count once per trace)",
    labelnames=("op", "group"))
_COLLECTIVE_BYTES = telemetry.counter(
    "collective_bytes_total",
    "Payload bytes entering collective ops, by op and group",
    labelnames=("op", "group"))
_COLLECTIVE_RETRIES = telemetry.counter(
    "collective_retries_total",
    "Eager collective attempts retried after a transient failure",
    labelnames=("op",))

# Eager-path timeout/retry policy — the same bounded-exponential-backoff
# discipline as the serving scheduler's wave retry (serving/scheduler.py
# _run_wave_with_retry): `retries` extra attempts, `backoff_s` doubling
# per retry, and `deadline_s` a hard budget on the whole retry window
# (None = attempts bound it alone). Applies ONLY to eager dispatches: a
# traced call site runs at trace time, where an exception is a program
# bug and a sleep would stall compilation — retrying there can't model
# a transient transport error. The `chaos.COLLECTIVE` fault point
# inside the barrier provokes the path deterministically.
_RETRY_POLICY = {"retries": 2, "backoff_s": 0.05, "deadline_s": None}
_UNSET = object()


def configure_retries(retries=None, backoff_s=None, deadline_s=_UNSET):
    """Tune (or disable, retries=0) the eager collective retry barrier.
    Returns the previous policy dict. The deadline_s default sentinel
    means "leave unchanged"; pass None explicitly to clear it."""
    prev = dict(_RETRY_POLICY)
    if retries is not None:
        _RETRY_POLICY["retries"] = max(0, int(retries))
    if backoff_s is not None:
        _RETRY_POLICY["backoff_s"] = float(backoff_s)
    if deadline_s is not _UNSET:
        _RETRY_POLICY["deadline_s"] = (None if deadline_s is None
                                       else float(deadline_s))
    return prev


def _eager_retry(fn, op, args, kwargs):
    """Run an eager collective behind the bounded backoff barrier.
    Every retry is counted (`collective_retries_total{op}`) and
    journaled as a `fault` event (kind `collective_error`), so a flaky
    transport shows up in the run journal next to the step events it
    slowed down; the final failure re-raises to the caller."""
    policy = dict(_RETRY_POLICY)
    retries = policy["retries"]
    delay = policy["backoff_s"]
    deadline = None if policy["deadline_s"] is None \
        else time.monotonic() + policy["deadline_s"]
    for attempt in range(retries + 1):
        try:
            if chaos.enabled():
                chaos.fire(chaos.COLLECTIVE, op=op, attempt=attempt)
            return fn(*args, **kwargs)
        except Exception as e:   # noqa: BLE001 — retry barrier
            out_of_budget = attempt >= retries or (
                deadline is not None
                and time.monotonic() + delay > deadline)
            recorder = _flight_recorder.get_recorder()
            if recorder is not None:
                recorder.fault(kind="collective_error",
                               action="raise" if out_of_budget else "retry",
                               error=repr(e), op=op, attempt=attempt)
            if out_of_budget:
                raise
            _COLLECTIVE_RETRIES.labels(op).inc()
            time.sleep(delay)
            delay *= 2


def _payload_bytes(x):
    """Bytes of a tensor / array / list-of-tensors payload; works on
    tracers too (shape/dtype are known at trace time)."""
    try:
        if isinstance(x, (list, tuple)):
            return sum(_payload_bytes(v) for v in x)
        a = x._data if isinstance(x, Tensor) else x
        shape = jnp.shape(a)
        return int(np.prod(shape, dtype=np.int64)) * jnp.dtype(a.dtype).itemsize
    except Exception:
        return 0


def _group_label(group):
    """Best-effort closed-cardinality group label: the bound mesh axis
    name when the group (handle or registered id) is resolvable, else
    'default' (no mesh side effects — labels must not instantiate the
    default mesh)."""
    if group is None:
        return "default"
    if isinstance(group, mesh_mod._Group):
        return str(group.axis_name)
    try:
        registered = mesh_mod._groups.get(int(group))
    except (TypeError, ValueError):
        registered = None
    if registered is not None:
        return str(registered.axis_name)
    return str(group)


def _payload_is_traced(x):
    if isinstance(x, (list, tuple)):
        return bool(x) and _payload_is_traced(x[0])
    return _in_trace(x._data if isinstance(x, Tensor) else x)


def _instrumented(payload_arg=0):
    """Wrap a collective op: count calls/bytes, journal, span."""
    def deco(fn):
        import inspect
        op = fn.__name__
        params = list(inspect.signature(fn).parameters)
        payload_name = params[payload_arg]
        group_arg = params.index("group") if "group" in params else None

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            payload = args[payload_arg] if len(args) > payload_arg \
                else kwargs.get(payload_name)
            nbytes = _payload_bytes(payload)
            grp = kwargs.get("group")
            if grp is None and group_arg is not None \
                    and len(args) > group_arg:
                grp = args[group_arg]
            group = _group_label(grp)
            traced = _payload_is_traced(payload)
            _COLLECTIVE_CALLS.labels(op, group).inc()
            _COLLECTIVE_BYTES.labels(op, group).inc(nbytes)
            recorder = _flight_recorder.get_recorder()
            if recorder is not None:
                recorder.collective(op=op, nbytes=nbytes, group=group,
                                    traced=traced)
            with profiler.RecordEvent(f"collective/{op}"):
                if traced:
                    # trace time: an exception here is a program bug,
                    # not a transient — no retry barrier
                    return fn(*args, **kwargs)
                return _eager_retry(fn, op, args, kwargs)
        return wrapper
    return deco


def _apply_inplace(x, arr):
    if isinstance(x, Tensor):
        x._data = arr
        return x
    return Tensor(arr)


@_instrumented(payload_arg=0)
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    a = as_array(tensor)
    if _in_trace(a):
        ax = _axis(group)
        if op == ReduceOp.SUM:
            out = lax.psum(a, ax)
        elif op == ReduceOp.MAX:
            out = lax.pmax(a, ax)
        elif op == ReduceOp.MIN:
            out = lax.pmin(a, ax)
        elif op == ReduceOp.AVG:
            out = lax.pmean(a, ax)
        else:
            out = jnp.exp(lax.psum(jnp.log(a), ax))
        return _apply_inplace(tensor, out)
    # eager single-controller: the full world is visible locally -> identity
    return _apply_inplace(tensor, a)


@_instrumented(payload_arg=1)
def all_gather(tensor_list, tensor, group=None, sync_op=True):
    a = as_array(tensor)
    if _in_trace(a):
        ax = _axis(group)
        gathered = lax.all_gather(a, ax)  # [axis_size, ...]
        n = gathered.shape[0]
        outs = [Tensor(gathered[i]) for i in range(n)]
    else:
        outs = [Tensor(a)]
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(outs)
    return outs


@_instrumented(payload_arg=1)
def reduce_scatter(tensor, tensor_or_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    src = tensor_or_list
    if isinstance(src, (list, tuple)):
        a = jnp.concatenate([as_array(t) for t in src], axis=0)
    else:
        a = as_array(src)
    if _in_trace(a):
        ax = _axis(group)
        out = lax.psum_scatter(a, ax, tiled=True)
    else:
        out = a
    return _apply_inplace(tensor, out)


@_instrumented(payload_arg=0)
def broadcast(tensor, src=0, group=None, sync_op=True):
    a = as_array(tensor)
    if _in_trace(a):
        ax = _axis(group)
        gathered = lax.all_gather(a, ax)
        return _apply_inplace(tensor, gathered[src])
    return _apply_inplace(tensor, a)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # SPMD: reduce == all_reduce (every shard holds the result; the dst
    # distinction only matters for MPMD runtimes)
    return all_reduce(tensor, op=op, group=group)


@_instrumented(payload_arg=0)
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    a = as_array(tensor)
    if _in_trace(a) and tensor_list is not None:
        ax = _axis(group)
        stacked = jnp.stack([as_array(t) for t in tensor_list])
        idx = lax.axis_index(ax)
        return _apply_inplace(tensor, stacked[idx])
    if tensor_list:
        return _apply_inplace(tensor, as_array(tensor_list[src]))
    return tensor


@_instrumented(payload_arg=0)
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    arrays = [as_array(t) for t in in_tensor_list]
    if _in_trace(arrays[0]):
        ax = _axis(group)
        stacked = jnp.stack(arrays)  # [n_peers, ...]
        out = lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0,
                             tiled=False)
        outs = [Tensor(out[i]) for i in range(out.shape[0])]
    else:
        outs = [Tensor(a) for a in arrays]
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(outs)
    return outs


@_instrumented(payload_arg=0)
def send(tensor, dst=0, group=None, sync_op=True):
    """p2p over a ring edge -> ppermute in traced mode (ref send_v2_op.cc)."""
    a = as_array(tensor)
    if _in_trace(a):
        ax = _axis(group)
        n = mesh_mod.get_group(group).nranks
        perm = [(i, dst if n == 1 else (i + (dst or 1)) % n) for i in range(n)]
        return Tensor(lax.ppermute(a, ax, perm))
    return tensor


@_instrumented(payload_arg=0)
def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def barrier(group=None):
    try:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except (RuntimeError, ValueError):
        pass


def wait(tensor, group=None, use_calc_stream=True):
    """c_sync_*_stream analog: XLA orders async collectives itself; blocking
    on the value is the only observable semantics."""
    a = as_array(tensor)
    if not _in_trace(a):
        a.block_until_ready()
    return tensor


def new_group(ranks=None, backend=None, timeout=None):
    """Bind a new group to the innermost mesh axis by default."""
    axes = mesh_mod.mesh_axes() or (mesh_mod.DP_AXIS,)
    return mesh_mod.register_group(axes[-1], ranks)


def get_group(gid=0):
    return mesh_mod.get_group(gid)


# --------------------------------------------------------- TP split helpers

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """ref collective.py:492 paddle.distributed.split — Megatron-style parallel
    linear/embedding. The TPU-native implementation lives in
    distributed/parallel_layers.py (sharding annotations instead of manual
    allreduce); this functional form keeps reference-API compat."""
    from .parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unknown split operation {operation!r}")
