"""ShardedTrainStep — the multi-chip compiled training step.

TPU-native replacement for the reference's multi-device executors
(ref framework/details/ SSA-graph ParallelExecutor + imperative Reducer +
fleet meta-optimizer program rewrites): ONE jit over a Mesh.
  - batch sharded on 'dp' (+ optionally 'sp' along sequence)
  - params/opt-state sharded per-tensor from Parameter.sharding
    PartitionSpec hints ('mp' Megatron layouts come from the model)
  - ZeRO: optimizer states (and optionally params) additionally sharded over
    'dp' (PAPERS.md arXiv:2004.13336 cross-replica weight-update sharding)
  - XLA SPMD partitioner inserts + schedules all collectives over ICI
    (gradient AllReduce, TP AllReduces, AllGathers) — bucketing/overlap is
    the compiler's latency-hiding scheduler.
  - exact-resume + elastic reshard (docs/robustness.md): the step fires
    the same `chaos.TRAIN_STEP` kill point, fuses the same grad-norm /
    non-finite sentinel, and carries the same flight-recorder
    instrumentation as the single-chip TrainStep, so
    `scripts/chaos_train.py --mesh dp=N --resume-mesh dp=M` can prove a
    killed sharded run resumes bitwise-identically onto a DIFFERENT
    replica count. `sync()` gathers the dp-sharded optimizer slots into
    host copies (the PR-7 optimizer-copy contract, per shard), and
    `sharding_state()` is what `Model.save` records in the `.pdtrain`
    payload so a resume can re-derive placements on the new mesh.

    The `exact_reshard` flag (opt-in: constructor kwarg or fleet
    `sharding_configs={"stage": 1, "exact_reshard": True}`) selects
    STORAGE-sharded, math-replicated execution: every dp-sharded state
    leaf is gathered to its full logical shape before arithmetic
    touches it (`with_sharding_constraint` to replicated), the whole
    forward/backward/update computes at dp-invariant tile shapes, and
    the out_shardings slice results back to their shards. The only
    dp-dependent collectives are all-gather (concatenation) and
    dynamic-slice — both bitwise-clean — so with a batch the mesh
    cannot dp-shard (leading dim not divisible), the per-step
    (loss, grad-norm, params, moments) are bit-identical across dp
    counts: a dp=2 checkpoint resumes on dp=4 bitwise. Measured on
    this XLA build, the default drifts by ~1 ulp per step across dp
    counts: per-shard tile geometry changes the compiler's fma/fusion
    choices even for the purely elementwise Adam update, and a
    dp-sharded batch's gradient psum tree reorders with dp. The
    default (False) keeps full ZeRO compute sharding —
    reduce-scattered grads, shard-local update math and transients —
    the right trade when throughput matters more than cross-mesh
    exactness; kill/resume onto the SAME mesh is bitwise in both
    modes, and storage stays sharded either way
    (`opt_specs`/`param_specs`), so the persistent opt-state residency
    win of arXiv:2004.13336 always holds.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework import state
from ..framework.tensor import Tensor
from ..jit import InstrumentedStepMixin, grad_norm_sentinel
from ..utils import chaos, telemetry
from . import mesh as mesh_mod

#: per-device bytes of the dp-sharded optimizer state gathered at the
#: last checkpoint sync — the live measurement of ZeRO's memory win
#: (total state bytes / dp when sharding engaged; catalog:
#: docs/observability.md)
_SHARD_BYTES = telemetry.gauge(
    "checkpoint_shard_bytes",
    "Per-device bytes of dp-sharded optimizer state at the last "
    "checkpoint sync")


def _shard_nbytes(arr):
    """Per-device bytes of one (possibly sharded) array."""
    try:
        shape = arr.sharding.shard_shape(arr.shape)
    except Exception:
        shape = arr.shape
    return int(np.prod(shape, dtype=np.int64)) * arr.dtype.itemsize


def _spec_doc(spec):
    """PartitionSpec -> picklable list (axis name, None, or list of
    names per dim) for the `.pdtrain` sharding record."""
    return [list(e) if isinstance(e, (tuple, list)) else e for e in spec]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _unwrap(v) for k, v in x.items()}
    return x


def _wrap(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return x


def _valid_spec(spec, mesh, shape):
    """Keep only axes present in the mesh and divisible dims; else replicate."""
    if spec is None:
        return P()
    parts = list(spec)
    out = []
    for i, p in enumerate(parts):
        if p is None or p not in mesh.axis_names:
            out.append(None)
            continue
        if i < len(shape) and shape[i] % mesh.shape[p] == 0:
            out.append(p)
        else:
            out.append(None)
    return P(*out) if any(o is not None for o in out) else P()


def _zero_spec(shape, mesh, dp_axis, base_spec):
    """Shard the largest unsharded dim over dp for opt-state (ZeRO-1)."""
    if dp_axis not in mesh.axis_names or not shape:
        return base_spec
    dp = mesh.shape[dp_axis]
    parts = list(base_spec) + [None] * (len(shape) - len(list(base_spec)))
    for i in np.argsort([-s for s in shape]):
        if parts[i] is None and shape[i] % dp == 0:
            parts[i] = dp_axis
            return P(*parts)
    return base_spec


class ShardedTrainStep(InstrumentedStepMixin):
    """Compiled SPMD train step over the current Mesh.

    Usage:
        make_mesh({'dp': 2, 'mp': 4})
        step = ShardedTrainStep(model, loss_fn, opt, zero_stage=1)
        loss = step(batch_inputs, batch_labels)   # global batch arrays
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None, dp_axis=None,
                 zero_stage=0, donate=True, remat=False, shard_seq=True,
                 return_outputs=False, exact_reshard=False):
        from ..jit import transforms as tfm
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.return_outputs = return_outputs
        self.mesh = mesh or mesh_mod.get_mesh() or mesh_mod.default_mesh()
        self.dp_axis = dp_axis or (
            mesh_mod.DP_AXIS if mesh_mod.DP_AXIS in self.mesh.axis_names
            else self.mesh.axis_names[0])
        # strategy transforms from the fleet meta-optimizer chain override
        # the constructor defaults (jit/transforms.py)
        self.transforms = tfm.resolve(optimizer)
        zero_stage = tfm.zero_stage_of(self.transforms, zero_stage)
        remat = remat or self.transforms.get("recompute") is not None
        self.zero_stage = zero_stage
        self.shard_seq = shard_seq
        # deterministic-elastic mode rides the sharding strategy too
        # (fleet sharding_configs={"stage": 1, "exact_reshard": True}),
        # so fit-built steps can opt in without new plumbing
        sh_cfg = self.transforms.get("sharding") or {}
        if "exact_reshard" in sh_cfg:
            exact_reshard = bool(sh_cfg["exact_reshard"])
        self.exact_reshard = bool(exact_reshard)

        params, buffers = model.functional_state()
        named_params = dict(model.named_parameters())

        # ---- param shardings from Parameter.sharding hints
        self.param_specs = {}
        for n, arr in params.items():
            hint = getattr(named_params[n], "sharding", None)
            self.param_specs[n] = _valid_spec(hint, self.mesh, arr.shape)
        self.buffer_specs = {n: P() for n in buffers}

        # ---- optimizer state shardings (follow param; + dp for ZeRO>=1)
        # ZeRO stages under GSPMD (ref fleet sharding_optimizer.py stages;
        # PAPERS.md arXiv:2004.13336):
        #   1: optimizer state dp-sharded — the update math runs on 1/dp of
        #      each state tensor per device.
        #   2: gradient sharding. Grads are ephemeral inside the single
        #      compiled step and are consumed by the dp-sharded update, so
        #      the partitioner materialises them reduce-SCATTERED into the
        #      update — stage 2 is subsumed by stage 1 here (there is no
        #      standalone grad buffer to shard).
        #   3: parameters dp-sharded too. Gather-on-use is explicit in the
        #      partitioned HLO: every use site all-gathers the shard just
        #      before the matmul and the backward reduce-scatters dL/dW
        #      straight back to the shard (test_zero3.py asserts both
        #      collectives exist and per-device bytes are size/dp).
        # parameters= threads the live Parameter objects through so an
        # optimizer carrying RESTORED accumulators (checkpoint resume —
        # possibly written on a DIFFERENT mesh) seeds the functional
        # state; device_put below then reshards the restored host
        # copies onto THIS mesh's placements (the elastic-reshard load
        # path). Without it a rebuilt sharded step would zero the
        # moments on every resume, exactly the TrainStep bug PR 10
        # fixed on the single-chip path.
        opt_state = optimizer.init_opt_state(
            params, parameters=named_params)
        self.opt_specs = {}
        for n, slots in opt_state.items():
            base = self.param_specs[n]
            spec = base
            if zero_stage >= 1:
                spec = _zero_spec(params[n].shape, self.mesh, self.dp_axis,
                                  base)
            self.opt_specs[n] = {sn: spec for sn in slots}
        if zero_stage >= 3:
            for n, arr in params.items():
                self.param_specs[n] = _zero_spec(arr.shape, self.mesh,
                                                 self.dp_axis,
                                                 self.param_specs[n])

        def shard(x, spec):
            # jnp.copy BEFORE the placement: a restored/set_value'd leaf
            # can be a ZERO-COPY view of host numpy memory (jax 0.4.37's
            # CPU client aliases aligned numpy buffers), and the
            # compiled step DONATES these — XLA freeing memory numpy
            # owns corrupts the heap ("double free"/"corrupted
            # double-linked list" on the first post-restore step). The
            # copy materializes an XLA-owned buffer first, exactly what
            # jit.TrainStep.__init__ does for the same reason;
            # construction-time-only cost.
            return jax.device_put(jnp.copy(x), NamedSharding(self.mesh, spec))

        self.params = {n: shard(a, self.param_specs[n])
                       for n, a in params.items()}
        self.buffers = {n: shard(a, P()) for n, a in buffers.items()}
        self.opt_state = jax.tree_util.tree_map_with_path(
            lambda kp, a: shard(a, self.opt_specs[kp[0].key][kp[1].key]),
            opt_state)
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()
        dp_axis_name = self.dp_axis
        mesh = self.mesh

        def _forward(p, buffers, key, inputs, labels):
            with state.functional_rng_ctx(key):
                # loss may read model params directly (CRF transitions,
                # tied heads): keep the traced substitution alive through it
                # (same fix as jit.TrainStep._forward)
                with model._use_state(p, buffers):
                    out, new_buf = model.functional_call(
                        p, buffers, *_wrap(inputs))
                    outs = out if isinstance(out, tuple) else (out,)
                    loss_t = loss_fn(*outs, *_wrap(labels))
            return _unwrap(loss_t), (new_buf, _unwrap(out))

        # amp autocast (recompute is handled by the remat flag below so a
        # strategy-enabled recompute isn't checkpointed twice)
        amp_only = {k: v for k, v in self.transforms.items() if k == "amp"}
        _forward = tfm.wrap_forward(_forward, amp_only)
        if remat:
            from ..jit.transforms import _remat_policy
            _forward = jax.checkpoint(
                _forward, static_argnums=(),
                policy=_remat_policy(self.transforms.get("recompute")))

        # k-step gradient merge (strategy.gradient_merge): accumulator
        # sharded like the grads (= params)
        k_merge, merge_avg = tfm.merge_config(self.transforms)
        self.grad_acc = tfm.init_grad_acc(params, k_merge)
        if k_merge > 1:
            self.grad_acc = {n: shard(a, self.param_specs[n])
                             for n, a in self.grad_acc.items()}
        update_fn = tfm.merged_update(apply_fn, k_merge, merge_avg)

        # fp16_allreduce (strategy.fp16_allreduce, ref fleet
        # fp16_allreduce_optimizer.py): make the DP gradient reduction an
        # EXPLICIT cast -> psum('dp') -> upcast by computing grads inside a
        # shard_map that is manual over the dp axis only (mp/sp/ep stay
        # GSPMD-auto) — halves DP grad bytes over ICI. Incompatible with
        # ZeRO-3 (grads must reduce-scatter to the param shard, not
        # all-reduce) and with return_outputs (per-shard aux outputs).
        fp16_ar = self.transforms.get("fp16_allreduce")
        if fp16_ar and (zero_stage >= 3 or return_outputs
                        or self.mesh.shape[dp_axis_name] == 1):
            import warnings
            warnings.warn(
                "fp16_allreduce ignored: needs dp>1 and is incompatible "
                "with ZeRO-3 / return_outputs")
            fp16_ar = None
        self.fp16_allreduce = bool(fp16_ar)

        if fp16_ar:
            red_dt = tfm.reduced_dtype(fp16_ar.get("dtype"))
            dp_n = mesh.shape[dp_axis_name]

            def _grad_body(p, buffers, key, inputs, labels):
                # decorrelate per-shard randomness (dropout masks must
                # differ across dp shards like the GSPMD global draw)
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(dp_axis_name))

                def pure_loss(p_):
                    return _forward(p_, buffers, key, inputs, labels)

                (loss, (new_buf, _)), grads = jax.value_and_grad(
                    pure_loss, has_aux=True)(p)
                # the explicit reduced-precision DP reduction; dividing
                # BEFORE the cast keeps the fp16 sum in range (the mean
                # is identical; the sum of dp_n unscaled grads can
                # overflow fp16's 65504 max at large dp)
                grads = jax.tree.map(
                    lambda g: jax.lax.psum(
                        (g / dp_n).astype(red_dt), dp_axis_name
                    ).astype(g.dtype)
                    if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
                loss = jax.lax.pmean(loss, dp_axis_name)
                # float buffers (e.g. BN running stats from local batch
                # stats) are averaged across dp shards; int counters are
                # dp-invariant already
                new_buf = jax.tree.map(
                    lambda b: jax.lax.pmean(b, dp_axis_name)
                    if jnp.issubdtype(b.dtype, jnp.floating) else b,
                    new_buf)
                return loss, new_buf, grads

            def _in_spec_tree(tree, spec):
                return jax.tree.map(lambda _: spec, tree)

        def _batch_dp_spec(a):
            # mirror _shard_batch: only leading dims divisible by dp are
            # dp-sharded; scalars / ragged batches stay replicated
            if (getattr(a, "ndim", 0) >= 1
                    and a.shape[0] % mesh.shape[dp_axis_name] == 0):
                return P(dp_axis_name)
            return P()

        exact = self.exact_reshard

        def _step(params, buffers, opt_state, acc, key, lr, step_i,
                  inputs, labels):
            if exact:
                # storage-sharded, math-replicated: gather every sharded
                # state leaf to its full logical shape BEFORE any
                # arithmetic touches it. Elementwise update math is then
                # compiled at dp-invariant tile shapes (XLA's fma/fusion
                # choices depend on the per-shard tile geometry — at
                # dp=2 vs dp=4 the same Adam update rounds differently
                # by 1 ulp otherwise), and the out_shardings slice the
                # results back to their shards. The collectives this
                # inserts (all-gather = concat in, dynamic-slice out)
                # are bitwise-clean, which is the whole point.
                rep = NamedSharding(mesh, P())

                def _gather(t):
                    return jax.tree.map(
                        lambda a: jax.lax.with_sharding_constraint(a, rep),
                        t)

                params = _gather(params)
                opt_state = _gather(opt_state)
                acc = _gather(acc)
            if fp16_ar:
                batch_spec = jax.tree.map(_batch_dp_spec, inputs)
                label_spec = jax.tree.map(_batch_dp_spec, labels)
                grad_fn = jax.shard_map(
                    _grad_body, mesh=mesh, axis_names={dp_axis_name},
                    in_specs=(_in_spec_tree(params, P()),
                              _in_spec_tree(buffers, P()), P(),
                              batch_spec, label_spec),
                    out_specs=(P(), _in_spec_tree(buffers, P()),
                               _in_spec_tree(params, P())),
                    check_vma=False)
                loss, new_buf, grads = grad_fn(params, buffers, key,
                                               inputs, labels)
                outs = ()
            else:
                def pure_loss(p):
                    return _forward(p, buffers, key, inputs, labels)

                (loss, (new_buf, outs)), grads = jax.value_and_grad(
                    pure_loss, has_aux=True)(params)
                if exact:
                    # pin the backward's results REPLICATED before the
                    # dp-sharded update reads them: sharding propagation
                    # then computes the whole backward at full logical
                    # shapes on every device (dp-count-invariant
                    # reduction trees — the bitwise elastic-reshard
                    # contract, see module docstring), instead of
                    # materializing reduce-scattered grads whose
                    # per-shard tile geometry varies with dp
                    rep = NamedSharding(mesh, P())
                    grads = jax.tree.map(
                        lambda g: jax.lax.with_sharding_constraint(g, rep),
                        grads)
            new_params, new_opt, new_acc = update_fn(
                params, grads, opt_state, acc, lr, step_i)
            # the SAME fused sentinel as jit.TrainStep (one shared
            # implementation — the (loss, grad_norm) pair IS what the
            # kill/resume parity gate compares across step flavours).
            # Under exact_reshard the grads are pinned replicated (the
            # fp16 path's psum out_specs already are), so the reduction
            # runs at full logical shape on every device —
            # dp-count-invariant.
            grad_norm, notfinite = grad_norm_sentinel(loss, grads)
            return (loss, new_params, new_buf, new_opt, new_acc, outs,
                    grad_norm, notfinite)

        # output shardings mirror inputs so state stays put across steps
        ns = lambda spec: NamedSharding(mesh, spec)
        param_sh = {n: ns(s) for n, s in self.param_specs.items()}
        buffer_sh = {n: ns(P()) for n in self.buffers}
        opt_sh = {n: {sn: ns(s) for sn, s in slots.items()}
                  for n, slots in self.opt_specs.items()}
        acc_sh = {n: param_sh[n] for n in self.grad_acc}
        donate_args = (0, 1, 2, 3) if donate else ()
        # the declaration of record for the program-level audit
        # (tools/jxaudit, xprof sharded_train_step_spec) — PjitFunction
        # exposes no public donate introspection
        self._donate_argnums = donate_args
        self._compiled = jax.jit(
            _step,
            in_shardings=(param_sh, buffer_sh, opt_sh, acc_sh, None, None,
                          None, None, None),
            out_shardings=(ns(P()), param_sh, buffer_sh, opt_sh, acc_sh,
                           None, ns(P()), ns(P())),
            donate_argnums=donate_args,
        )
        # flight-recorder instrumentation (attach_flight_recorder); the
        # label keys xla_compiles_total{function=} and matches the
        # xprof registry's tracked-program name
        self._init_instrumentation(label="sharded_train_step")

    # ------------------------------------------------------------------ step
    def _shard_batch(self, arrs):
        # dim 1 = sequence is a sequence-model convention; pass
        # shard_seq=False for models where dim 1 isn't a sequence axis
        sp = mesh_mod.SP_AXIS if (
            self.shard_seq
            and mesh_mod.SP_AXIS in self.mesh.axis_names) else None
        out = []
        for a in arrs:
            a = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            parts = [None] * a.ndim
            if a.ndim >= 1 and a.shape[0] % self.mesh.shape[self.dp_axis] == 0:
                parts[0] = self.dp_axis
            # sequence dim rides 'sp' (ring attention shards activations too)
            if (sp and a.ndim >= 2
                    and a.shape[1] % self.mesh.shape[sp] == 0):
                parts[1] = sp
            spec = P(*parts) if any(parts) else P()
            out.append(jax.device_put(a, NamedSharding(self.mesh, spec)))
        return tuple(out)

    def __call__(self, inputs, labels):
        if chaos.enabled():
            # same kill/stall boundary as jit.TrainStep: host-side,
            # BEFORE the step counter, the RNG draw, or the compiled
            # dispatch — a raise here leaves every piece of (sharded)
            # training state exactly at the last completed step
            chaos.fire(chaos.TRAIN_STEP, step=self._step_i + 1)
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        args = (self.params, self.buffers, self.opt_state, self.grad_acc,
                state.next_rng_key(), lr,
                jnp.asarray(self._step_i, jnp.int32),
                self._shard_batch(inputs), self._shard_batch(labels))
        with self.mesh:
            if self._recorder is not None:
                loss, outs = self._instrumented_call(args)
            else:
                (loss, self.params, self.buffers, self.opt_state,
                 self.grad_acc, outs, self._last_grad_norm,
                 self._last_nonfinite) = self._compiled(*args)
        if self.return_outputs:
            return Tensor(loss), _wrap(outs)
        return Tensor(loss)

    def sync(self):
        """Write functional state back into the Layer/Optimizer objects.
        The dp-sharded optimizer slots are GATHERED into host copies
        (device_get on a sharded array assembles the full logical
        array), so the snapshot `optimizer.state_dict()` hands the
        checkpoint survives later donated steps — the PR-7 optimizer-
        copy contract, now per shard. `checkpoint_shard_bytes` records
        the per-device footprint of what was gathered (the live ZeRO
        memory-win measurement)."""
        named_p = dict(self.model.named_parameters())
        for n, arr in self.params.items():
            named_p[n]._data = jnp.copy(jax.device_get(arr))
        named_b = dict(self.model.named_buffers())
        for n, arr in self.buffers.items():
            named_b[n]._data = jnp.copy(jax.device_get(arr))
        opt = self.optimizer
        opt._global_step = self._step_i
        stale = None
        if chaos.enabled():
            # positive control for the reshard parity harness
            # (--inject stale-shard): a gather that silently loses the
            # dp shards' updates for one parameter's slots must make
            # the kill/resume parity check fail
            stale = chaos.value(chaos.SHARD_STATE, default=None)
        shard_bytes = 0
        stale_hit = False
        for n, slots in self.opt_state.items():
            host = {}
            for sn, arr in slots.items():
                shard_bytes += _shard_nbytes(arr)
                full = jnp.asarray(jax.device_get(arr))
                if stale is not None and not stale_hit and \
                        (stale is True or str(stale) in n):
                    full = jnp.zeros_like(full)
                host[sn] = full
            if stale is not None and not stale_hit and \
                    (stale is True or str(stale) in n):
                stale_hit = True
            opt._accumulators[id(named_p[n])] = host
        _SHARD_BYTES.set(shard_bytes)

    def sharding_state(self):
        """The placement record `Model.save` embeds in the `.pdtrain`
        payload (utils/resume.capture_train_state): mesh shape, dp
        axis, ZeRO stage, and the per-leaf PartitionSpecs — everything
        a resume needs to KNOW how the checkpoint was laid out, and to
        journal the `reshard` event when the current mesh differs. The
        restore path re-derives placements for the CURRENT mesh (a
        fresh ShardedTrainStep device_puts the restored host copies),
        so these specs are provenance, not instructions."""
        return {
            "mesh": {name: int(self.mesh.shape[name])
                     for name in self.mesh.axis_names},
            "dp_axis": self.dp_axis,
            "zero_stage": int(self.zero_stage),
            "exact_reshard": bool(self.exact_reshard),
            "param_specs": {n: _spec_doc(s)
                            for n, s in self.param_specs.items()},
            "opt_specs": {n: {sn: _spec_doc(s)
                              for sn, s in slots.items()}
                          for n, slots in self.opt_specs.items()},
        }

    def audit_sharding_decl(self):
        """Declared-sharding record for the mesh-aware program audit
        (tools/jxaudit/mesh_rules.py, threaded through the xprof
        registry's sharded_train_step spec). Hands out the LIVE
        PartitionSpec trees the compiled step was built with — the audit
        compares these against what XLA committed to in the optimized
        HLO, and because they are the same objects `jax.jit` received,
        the declarations cannot drift from the code.

        `in_specs` is keyed by positional argnum of `_step`
        (params, buffers, opt_state, acc); batch/scalar args are
        unconstrained at jit time and carry no declaration.
        `expected_collectives` whitelists collective opcodes the
        reshard-in-body rule must NOT flag: the flash-attention kernel's
        shifted-window slice/pad partitions into halo-exchange
        collective-permutes under GSPMD whenever the batch dim doesn't
        divide dp — data movement the kernel's math asked for, not an
        implicit reshard (their exact counts are still gated by the
        collective-budget rows)."""
        return {
            "mesh_axes": {name: int(self.mesh.shape[name])
                          for name in self.mesh.axis_names},
            "in_specs": {
                0: dict(self.param_specs),
                1: dict(self.buffer_specs),
                2: {n: dict(slots)
                    for n, slots in self.opt_specs.items()},
                3: {n: self.param_specs[n] for n in self.grad_acc},
            },
            # exact_reshard pins state/grads replicated via explicit
            # with_sharding_constraint sites; sharding-dropped checks the
            # traced program still carries them
            "constraint_specs": [repr(P())] if self.exact_reshard else [],
            "expected_collectives": ("collective-permute",),
        }
