"""Host-side (CPU) collectives — the GlooWrapper analog.

The reference carries gloo for everything that must synchronise OUTSIDE
the accelerator ring: role_maker rendezvous, fleet.util barriers,
dataset global shuffle, distributed metric aggregation
(ref framework/fleet/gloo_wrapper.h:113, platform/gloo_context.cc,
fleet/base/role_maker.py:33 — gloo over HTTP/file/HDFS kv stores).

TPU-native stance: device collectives are XLA's job (lax.psum over the
mesh); the HOST control plane still needs its own rendezvous, so this
module provides a dependency-free kv-store + collective set:

  - KVStore        — tiny TCP key/value service (set/get-wait/add), the
                     HTTP-store analog; values are opaque bytes
  - FileKVStore    — shared-filesystem store (the file-store analog)
  - HostCollective — rank/world barrier, all_gather, broadcast,
                     all_reduce(np) built on either store

Wire format (TCP): one JSON line per request/response, values base64 —
control-plane sized payloads, no pickle on the wire.
"""
import base64
import json
import os
import socket
import socketserver
import threading
import time

import numpy as np


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.store
        for raw in self.rfile:
            try:
                req = json.loads(raw)
                op = req["op"]
                key = req.get("key", "")
                if op == "set":
                    with store.cond:
                        store.data[key] = base64.b64decode(req["val"])
                        store.cond.notify_all()
                    resp = {"ok": True}
                elif op == "get":
                    deadline = time.time() + float(req.get("timeout", 60))
                    with store.cond:
                        while key not in store.data:
                            left = deadline - time.time()
                            if left <= 0:
                                break
                            store.cond.wait(left)
                        val = store.data.get(key)
                    if val is None:
                        resp = {"ok": False, "err": f"timeout on {key!r}"}
                    else:
                        resp = {"ok": True,
                                "val": base64.b64encode(val).decode()}
                elif op == "delete":
                    with store.cond:
                        store.data.pop(key, None)
                    resp = {"ok": True}
                elif op == "add":
                    with store.cond:
                        cur = int(store.data.get(key, b"0"))
                        cur += int(req.get("delta", 1))
                        store.data[key] = str(cur).encode()
                        store.cond.notify_all()
                    resp = {"ok": True, "val": cur}
                else:
                    resp = {"ok": False, "err": f"bad op {op!r}"}
            except Exception as e:  # keep the store alive on bad input
                resp = {"ok": False, "err": f"{type(e).__name__}: {e}"}
            self.wfile.write(json.dumps(resp).encode() + b"\n")
            self.wfile.flush()


class KVStore:
    """TCP kv service. Start on rank 0 (or a dedicated host); every rank
    connects with KVClient. ref role_maker's HTTP kv store."""

    def __init__(self, port=0, host="0.0.0.0"):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.data = {}
        self.cond = threading.Condition()
        self._srv = _Srv((host, port), _Handler)
        self._srv.store = self
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class KVClient:
    def __init__(self, host="127.0.0.1", port=None):
        self._addr = (host, int(port))
        self._sock = None

    def _conn(self):
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=120)
            self._file = self._sock.makefile("rb")
        return self._sock

    def _rpc(self, req):
        s = self._conn()
        s.sendall(json.dumps(req).encode() + b"\n")
        resp = json.loads(self._file.readline())
        if not resp.get("ok"):
            raise RuntimeError(f"kv store: {resp.get('err')}")
        return resp

    def set(self, key, val: bytes):
        self._rpc({"op": "set", "key": key,
                   "val": base64.b64encode(val).decode()})

    def get(self, key, timeout=60) -> bytes:
        r = self._rpc({"op": "get", "key": key, "timeout": timeout})
        return base64.b64decode(r["val"])

    def add(self, key, delta=1) -> int:
        return int(self._rpc({"op": "add", "key": key,
                              "delta": delta})["val"])

    def delete(self, key):
        self._rpc({"op": "delete", "key": key})

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class FileKVStore:
    """Shared-filesystem store (ref role_maker file-store rendezvous):
    one file per key under `root`; works across hosts on NFS-like FS."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        safe = base64.urlsafe_b64encode(key.encode()).decode()
        return os.path.join(self.root, safe)

    def set(self, key, val: bytes):
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(val)
        os.replace(tmp, self._path(key))

    def get(self, key, timeout=60) -> bytes:
        deadline = time.time() + timeout
        p = self._path(key)
        while time.time() < deadline:
            try:
                with open(p, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                time.sleep(0.02)
        raise RuntimeError(f"kv store: timeout on {key!r}")

    def add(self, key, delta=1) -> int:
        # cross-process atomicity via a lock file
        lock = self._path(key) + ".lock"
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                time.sleep(0.005)
        try:
            try:
                cur = int(self.get(key, timeout=0.01))
            except RuntimeError:
                cur = 0
            cur += delta
            self.set(key, str(cur).encode())
            return cur
        finally:
            os.close(fd)
            os.unlink(lock)

    def delete(self, key):
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def close(self):
        pass


class HostCollective:
    """Rank/world collectives over a kv store (GlooWrapper analog).
    Generation counters make the primitives reusable (each call uses a
    fresh key namespace), and each completed generation deletes the
    PREVIOUS generation's keys: completing gen g proves every rank
    finished gen g-1 (a rank only posts to g after its g-1 call
    returned), so the store stays O(world) keys per primitive instead of
    growing for the life of the job."""

    def __init__(self, rank, world, store, scope="default"):
        self.rank = int(rank)
        self.world = int(world)
        self.store = store
        self.scope = scope
        self._gen = {}
        self._prev_keys = {}   # kind -> keys of the previous generation

    def _key(self, kind, *extra):
        g = self._gen.get(kind, 0)
        self._gen[kind] = g + 1
        parts = [self.scope, kind, str(g)] + [str(e) for e in extra]
        return "/".join(parts), g

    def _cleanup(self, kind, keys):
        """Called on completing a generation: rank 0 deletes the previous
        generation's keys and remembers this one's for next time."""
        if self.rank == 0:
            for k in self._prev_keys.get(kind, ()):
                self.store.delete(k)
        self._prev_keys[kind] = keys

    def barrier(self, timeout=120):
        key, g = self._key("barrier")
        n = self.store.add(key, 1)
        done = f"{key}/done"
        if n == self.world:
            self.store.set(done, b"1")
        self.store.get(done, timeout=timeout)
        self._cleanup("barrier", [key, done])

    def all_gather(self, data: bytes, timeout=120):
        """Returns list of every rank's bytes, rank-ordered."""
        base, g = self._key("allgather")
        self.store.set(f"{base}/{self.rank}", data)
        out = []
        for r in range(self.world):
            out.append(self.store.get(f"{base}/{r}", timeout=timeout))
        self._cleanup("allgather",
                      [f"{base}/{r}" for r in range(self.world)])
        return out

    def broadcast(self, data, src=0, timeout=120):
        base, g = self._key("bcast")
        if self.rank == src:
            self.store.set(base, data)
        else:
            data = self.store.get(base, timeout=timeout)
        self._cleanup("bcast", [base])
        return data

    def all_reduce(self, arr, op="sum", timeout=120):
        """Small-array host allreduce (metrics, role bookkeeping)."""
        a = np.asarray(arr)
        parts = self.all_gather(a.tobytes(), timeout=timeout)
        stack = np.stack([np.frombuffer(p, dtype=a.dtype).reshape(a.shape)
                          for p in parts])
        if op == "sum":
            return stack.sum(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        raise ValueError(f"unknown op {op!r}")


def collective_from_env():
    """Build a HostCollective from the launcher env, or None when not in
    a distributed run. Honors PADDLE_GLOO_HTTP_ENDPOINT (kv server) and
    PADDLE_GLOO_FS_PATH (shared-fs store) like the reference role_maker."""
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world <= 1:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    ep = os.environ.get("PADDLE_GLOO_HTTP_ENDPOINT")
    if ep:
        host, port = ep.rsplit(":", 1)
        return HostCollective(rank, world, KVClient(host, port))
    fs = os.environ.get("PADDLE_GLOO_FS_PATH")
    if fs:
        return HostCollective(rank, world, FileKVStore(fs))
    return None
