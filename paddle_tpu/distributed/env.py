"""Distributed environment (ref python/paddle/distributed/parallel.py:57
init_parallel_env + ParallelEnv).

On TPU pods, process-level topology comes from jax.distributed (coordination
service over DCN); within a host, all local chips belong to this process, so
"rank" means process index and collective work is expressed over the Mesh
rather than per-chip ranks (SPMD, not MPMD).
"""
import os

import jax


_initialized = False


class ParallelEnv:
    """ref fluid/dygraph/parallel.py ParallelEnv — env-var cluster spec."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                        os.environ.get("RANK", 0)))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                              os.environ.get("WORLD_SIZE", 1)))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.environ.get("FLAGS_selected_tpus",
                                             os.environ.get(
                                                 "FLAGS_selected_gpus", "0")
                                             ).split(",")[0])

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    local_rank = rank
    nranks = world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


def init_parallel_env():
    """Multi-host bootstrap. Under a single process (the common TPU case —
    all local chips visible), this is a no-op beyond mesh setup."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    # the launcher's contract (launch.py _rank_env): a dedicated coordinator
    # address + per-rank process id for the jax coordination service — the
    # TCP bootstrap analog of gen_comm_id_helper.cc:284
    coordinator = os.environ.get("COORDINATOR_ADDRESS")
    num_procs = int(os.environ.get("NUM_PROCESSES", env.world_size))
    proc_id = int(os.environ.get("PROCESS_ID", env.rank))
    if coordinator is None and env.world_size > 1 and env.trainer_endpoints:
        coordinator = env.trainer_endpoints[0]
    if coordinator is not None and num_procs > 1:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_procs,
                process_id=proc_id)
        except (RuntimeError, ValueError):
            pass  # already initialized or single-process testing
    from .mesh import default_mesh
    default_mesh()  # materialise the data-parallel mesh over all devices
    _initialized = True
    return env


def is_initialized():
    return _initialized


def get_rank():
    try:
        return jax.process_index()
    except (RuntimeError, ValueError):
        return ParallelEnv().rank


def get_world_size():
    try:
        return jax.process_count()
    except (RuntimeError, ValueError):
        return ParallelEnv().world_size
