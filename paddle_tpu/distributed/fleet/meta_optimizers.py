"""Meta-optimizers (ref fleet/meta_optimizers/*: AMP, Recompute, GradientMerge,
Lamb, Lars, LocalSGD, Sharding, Pipeline, GraphExecution chained by
StrategyCompiler base/strategy_compiler.py:89).

TPU-native: instead of rewriting ProgramDesc, each meta-optimizer wraps the
inner Optimizer and/or flags transforms applied at TrainStep compile time
(bf16 autocast, jax.remat segments, gradient accumulation, GSPMD weight-update
sharding). The chain is composed here, mirroring maximum_path_len_algo's
compatibility ordering.
"""
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer, Lamb, Lars


class MetaOptimizerBase(Optimizer):
    def __init__(self, inner_opt):
        self.inner_opt = inner_opt
        # delegate core surface
        self._lr = inner_opt._lr
        self._parameters = inner_opt._parameters
        self._grad_clip = inner_opt._grad_clip
        self._weight_decay = inner_opt._weight_decay
        self._accumulators = inner_opt._accumulators
        # transform flags consumed by TrainStep/hapi
        self.transforms = dict(getattr(inner_opt, "transforms", {}))

    # the step counter lives on the INNER optimizer (checkpoints restore
    # it there via the delegated set_state_dict, and state_dict reads it
    # back from there) — a snapshot copy at wrap time would let the
    # wrapper and inner counters drift, so a rebuilt train step seeded
    # from the wrapper would restart its Adam bias correction and step
    # numbering at 0 after a resume
    @property
    def _global_step(self):
        return self.inner_opt._global_step

    @_global_step.setter
    def _global_step(self, value):
        self.inner_opt._global_step = value

    # default passthroughs
    def get_lr(self):
        return self.inner_opt.get_lr()

    def step(self):
        self.inner_opt.step()

    def clear_grad(self):
        self.inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss)

    def state_dict(self):
        return self.inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self.inner_opt.set_state_dict(sd)

    def init_opt_state(self, params, parameters=None):
        return self.inner_opt.init_opt_state(params, parameters=parameters)

    def apply_gradients_fn(self):
        return self.inner_opt.apply_gradients_fn()

    @property
    def _state_names(self):
        return self.inner_opt._state_names


class AMPOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/amp_optimizer.py: wraps with bf16 autocast +
    GradScaler semantics (scaling defaults off for bf16 — see amp/)."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        cfg = configs or {}
        self.transforms["amp"] = {
            "level": "O2" if cfg.get("use_pure_bf16") or cfg.get("use_pure_fp16")
            else "O1",
            "dtype": "bfloat16",
            "init_loss_scaling": cfg.get("init_loss_scaling", 1.0),
        }


class RecomputeOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/recompute_optimizer.py + fluid RecomputeOptimizer
    (optimizer.py:4549): jax.checkpoint on marked segments."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        self.transforms["recompute"] = dict(configs or {"checkpoints": []})

    def backward(self, loss, **kwargs):
        loss.backward()

    def apply_optimize(self, loss, startup_program=None, params_grads=None):
        self.inner_opt.step()


class GradientMergeOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/gradient_merge_optimizer.py — k-step grad
    accumulation before the update. Eagerly: accumulate into .grad and step
    every k; compiled: the TrainStep wraps updates in lax.cond."""

    def __init__(self, inner_opt, k_steps=1, avg=True):
        super().__init__(inner_opt)
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc_step = 0
        self.transforms["gradient_merge"] = {"k_steps": self.k_steps,
                                             "avg": avg}

    def step(self):
        self._acc_step += 1
        if self._acc_step % self.k_steps != 0:
            return  # keep accumulating in .grad
        if self.avg and self.k_steps > 1:
            from ...framework.selected_rows import SelectedRows
            for p in self._parameters:
                if p.grad is None:
                    continue
                if isinstance(p.grad, SelectedRows):
                    p.grad.values = p.grad.values / self.k_steps
                else:
                    p.grad._data = p.grad._data / self.k_steps
        self.inner_opt.step()

    def clear_grad(self):
        if self._acc_step % self.k_steps == 0:
            self.inner_opt.clear_grad()

    clear_gradients = clear_grad


class LambOptimizer(MetaOptimizerBase):
    def __init__(self, inner_opt, configs=None):
        lamb = Lamb(learning_rate=inner_opt._lr,
                    parameters=inner_opt._parameters,
                    grad_clip=inner_opt._grad_clip,
                    **({k: v for k, v in (configs or {}).items()
                        if k in ("lamb_weight_decay", "beta1", "beta2",
                                 "epsilon")}))
        super().__init__(lamb)


class LarsOptimizer(MetaOptimizerBase):
    def __init__(self, inner_opt, configs=None):
        cfg = {k: v for k, v in (configs or {}).items()
               if k in ("lars_coeff", "lars_weight_decay", "epsilon")}
        momentum = getattr(inner_opt, "_momentum", 0.9)
        lars = Lars(learning_rate=inner_opt._lr, momentum=momentum,
                    parameters=inner_opt._parameters,
                    grad_clip=inner_opt._grad_clip, **cfg)
        super().__init__(lars)


class LocalSGDOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/localsgd_optimizer.py — run k local steps then
    average params across dp. Under GSPMD, param averaging is a psum at sync
    points; the compiled step takes a sync flag."""

    def __init__(self, inner_opt, k_steps=1):
        super().__init__(inner_opt)
        self.k_steps = k_steps
        self.transforms["localsgd"] = {"k_steps": k_steps}


class AdaptiveLocalSGDOptimizer(MetaOptimizerBase):
    """ref localsgd_optimizer.py AdaptiveLocalSGDOptimizer: the averaging
    interval follows the loss — next_k = clip(ceil(sqrt(lr_0 * loss /
    (lr * loss_0) * init_k)), 1, 16) at every sync."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        cfg = dict(configs or {})
        self.transforms["localsgd"] = {
            "adaptive": True,
            "init_k_steps": int(cfg.get("init_k_steps", 1)),
            "begin_step": int(cfg.get("begin_step", 1)),
        }


class DGCOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/dgc_optimizer.py DGCMomentumOptimizer: top-k
    sparsified grads with momentum correction + residual accumulation;
    consumed by distributed/dgc.py DGCTrainStep."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        cfg = dict(configs or {})
        sparsity = cfg.get("sparsity", [0.999])
        if isinstance(sparsity, (list, tuple)):
            sparsity = sparsity[-1]
        self.transforms["dgc"] = {
            "sparsity": float(sparsity),
            "rampup_begin_step": int(cfg.get("rampup_begin_step", 0) or 0)}


class ShardingOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/sharding_optimizer.py:33 (ZeRO): on TPU this is
    GSPMD weight-update/optimizer-state sharding (PAPERS.md: Automatic
    Cross-Replica Sharding of Weight Update, arXiv:2004.13336) — opt states get
    sharded PartitionSpecs over 'dp' instead of manual broadcast/reduce."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        self.transforms["sharding"] = dict(configs or {})


class PipelineOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/pipeline_optimizer.py + fluid PipelineOptimizer
    (optimizer.py:3718): micro-batch 1F1B over 'pp' mesh axis; consumed by
    distributed/pipeline.py."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        self.transforms["pipeline"] = dict(
            configs or {"accumulate_steps": 1, "micro_batch_size": 1})


class FP16AllReduceOptimizer(MetaOptimizerBase):
    """ref meta_optimizers/fp16_allreduce_optimizer.py: gradients are cast
    to reduced precision for the cross-replica allreduce and restored
    after — halves DP gradient traffic over ICI. Consumed by
    ShardedTrainStep via the 'fp16_allreduce' transform (the reduction
    becomes an explicit cast -> psum('dp') -> upcast in a partial-manual
    shard_map over the dp axis)."""

    def __init__(self, inner_opt, configs=None):
        super().__init__(inner_opt)
        cfg = dict(configs or {})
        cfg.setdefault("dtype", "float16")   # the reference's choice
        self.transforms["fp16_allreduce"] = cfg


class GraphExecutionOptimizer(MetaOptimizerBase):
    """ref graph_execution_optimizer.py — the whole-graph compiled execution;
    on TPU every TrainStep is already whole-graph XLA, so this is the identity
    terminal of the chain."""


def build_distributed_optimizer(optimizer, strategy):
    """StrategyCompiler analog (ref base/strategy_compiler.py:89): order
    matters — match the reference's valid chain AMP ∘ Recompute ∘ (Lamb|Lars)
    ∘ (Sharding|Pipeline|LocalSGD|GradientMerge) ∘ GraphExecution."""
    opt = optimizer
    # ref strategy auto mode: meta-optimizers that report
    # universally-applicable turn themselves on (_enable_strategy) when
    # the user hand-set nothing. On TPU the always-win is bf16 autocast;
    # the decision is LOCAL — the caller's strategy object is not mutated.
    auto_amp = False
    if getattr(strategy, "auto", False):
        explicit = any(getattr(strategy, f, False) for f in (
            "amp", "recompute", "sharding", "pipeline", "localsgd",
            "adaptive_localsgd", "dgc", "gradient_merge", "lamb", "lars",
            "fp16_allreduce"))
        auto_amp = not explicit
    if strategy.lamb:
        opt = LambOptimizer(opt, strategy.lamb_configs)
    elif strategy.lars:
        opt = LarsOptimizer(opt, strategy.lars_configs)
    if strategy.recompute:
        opt = RecomputeOptimizer(opt, strategy.recompute_configs)
    if strategy.amp or auto_amp:
        opt = AMPOptimizer(opt, strategy.amp_configs)
    if getattr(strategy, "fp16_allreduce", False):
        opt = FP16AllReduceOptimizer(
            opt, getattr(strategy, "fp16_allreduce_configs", None))
    if strategy.sharding:
        opt = ShardingOptimizer(opt, strategy.sharding_configs)
    if strategy.pipeline:
        opt = PipelineOptimizer(opt, strategy.pipeline_configs)
    if getattr(strategy, "adaptive_localsgd", False):
        opt = AdaptiveLocalSGDOptimizer(
            opt, getattr(strategy, "adaptive_localsgd_configs", None))
    elif strategy.localsgd:
        opt = LocalSGDOptimizer(opt, strategy.localsgd_configs.get("k_steps", 1))
    if strategy.dgc:
        opt = DGCOptimizer(opt, getattr(strategy, "dgc_configs", None))
    if strategy.gradient_merge:
        opt = GradientMergeOptimizer(
            opt, strategy.gradient_merge_configs.get("k_steps", 1),
            strategy.gradient_merge_configs.get("avg", True))
    if not isinstance(opt, MetaOptimizerBase):
        opt = GraphExecutionOptimizer(opt)
    return opt
