"""Heterogeneous PS training — the TPU answer to the reference's heter-PS /
PSGPU path (ref paddle/fluid/framework/fleet/heter_ps/heter_comm.h,
fleet/ps_gpu_wrapper.h: GPU workers with a device-side embedding cache in
front of host parameter-server tables).

Design (TPU-native, not a port):
  - DENSE parameters + optimizer state are RESIDENT on the device and update
    in place inside one donated compiled step (no per-step dense pull/push —
    the reference keeps dense on the worker GPU the same way).
  - SPARSE embedding rows live in the host PS sparse table (beyond-HBM
    capacity). Per batch: host computes unique ids, pulls only those rows,
    the compiled step takes grads w.r.t. the pulled block, and the sparse
    grads are pushed back asynchronously.
  - XLA needs static shapes: the unique-id block is padded to power-of-two
    buckets so re-compilation happens O(log max_unique) times, not per batch.
    Padding duplicates uids[0]; untouched duplicate rows receive zero grad
    through the gather VJP, so pushing them is a no-op add.
"""
import numpy as np
import jax
import jax.numpy as jnp


def _bucket(n, lo=64):
    b = lo
    while b < n:
        b <<= 1
    return b


class HeterPSTrainer:
    """Device-resident dense tower + host-PS sparse embeddings.

    loss_fn(dense_params, urows, inv, *batch) -> scalar loss, where
    `urows[inv]` recovers per-position embedding rows ([B*S, emb_dim]).

    dense update runs on-device with `optimizer` (paddle_tpu Optimizer);
    sparse update is the PS table's optimizer (server-side SGD).
    """

    def __init__(self, loss_fn, dense_params, optimizer, client,
                 sparse_table=1, emb_dim=8, donate=True):
        self.client = client
        self.sparse_table = sparse_table
        self.emb_dim = emb_dim
        self.optimizer = optimizer
        self.params = {n: jnp.asarray(a, jnp.float32)
                       for n, a in dense_params.items()}
        self.opt_state = optimizer.init_opt_state(self.params)
        self._step_i = 0
        apply_fn = optimizer.apply_gradients_fn()

        def _step(params, opt_state, urows, inv, lr, step_i, *batch):
            loss, (gp, grows) = jax.value_and_grad(
                lambda p, r: loss_fn(p, r, inv, *batch),
                argnums=(0, 1))(params, urows)
            new_params, new_opt = apply_fn(params, gp, opt_state, lr, step_i)
            return loss, new_params, new_opt, grows

        donate_args = (0, 1) if donate else ()
        self._compiled = jax.jit(_step, donate_argnums=donate_args)

    def step(self, ids, *batch):
        """One heter step. `ids` is any int array of embedding ids for the
        batch; `urows[inv]` has one row per flattened id position."""
        c = self.client
        ids = np.asarray(ids).ravel()
        if ids.size == 0:
            raise ValueError("HeterPSTrainer.step: empty ids batch")
        uids, inv = np.unique(ids, return_inverse=True)
        b = _bucket(len(uids))
        pad = b - len(uids)
        uids_p = np.concatenate([uids, np.full(pad, uids[0], uids.dtype)]) \
            if pad else uids
        urows = c.pull_sparse(self.sparse_table, uids_p, self.emb_dim)
        urows = np.asarray(urows, np.float32).reshape(b, self.emb_dim)
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, self.params, self.opt_state, grows = self._compiled(
            self.params, self.opt_state, jnp.asarray(urows),
            jnp.asarray(inv.astype(np.int32)), lr,
            jnp.asarray(self._step_i, jnp.int32), *batch)
        c.push_sparse_grad(self.sparse_table, uids_p, np.asarray(grows))
        return float(loss)

    def dense_state(self):
        """Host copies of the device-resident dense params."""
        return {n: np.asarray(a) for n, a in self.params.items()}
