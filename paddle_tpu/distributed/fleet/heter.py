"""Heterogeneous PS training — the TPU answer to the reference's heter-PS /
PSGPU path (ref paddle/fluid/framework/fleet/heter_ps/heter_comm.h,
fleet/ps_gpu_wrapper.h: GPU workers with a device-side embedding cache in
front of host parameter-server tables).

Design (TPU-native, not a port):
  - DENSE parameters + optimizer state are RESIDENT on the device and update
    in place inside one donated compiled step (no per-step dense pull/push —
    the reference keeps dense on the worker GPU the same way).
  - SPARSE embedding rows live in the host PS sparse table (beyond-HBM
    capacity). Per batch: host computes unique ids, pulls only those rows,
    the compiled step takes grads w.r.t. the pulled block, and the sparse
    grads are pushed back asynchronously.
  - XLA needs static shapes: the unique-id block is padded to power-of-two
    buckets so re-compilation happens O(log max_unique) times, not per batch.
    Padding duplicates uids[0]; untouched duplicate rows receive zero grad
    through the gather VJP, so pushing them is a no-op add.
"""
import numpy as np
import jax
import jax.numpy as jnp


def _bucket(n, lo=64):
    b = lo
    while b < n:
        b <<= 1
    return b


class HotRowCache:
    """Device-resident write-back cache of hot sparse rows (ref
    fleet/heter_ps/hashtable.h + heter_comm.h — the PSGPU device cache,
    redesigned for TPU: the id->slot hash/LRU CONTROL plane stays on the
    host, only the row DATA plane [capacity, dim] lives in HBM, indexed
    by static-shape gathers inside the compiled step).

    While a row is cached the device copy is AUTHORITATIVE: its update
    (SGD at the TRAINER's sparse_lr, the same rule the server applies on
    PUSH_SPARSE_GRAD — the update itself lives in the trainer's compiled
    step, not here; this class is the pure control+storage plane).
    Eviction (LRU) writes absolute rows back via the native SET_SPARSE
    command. Repeated-key batches therefore cost ZERO host round-trips."""

    def __init__(self, client, table_id, dim, capacity):
        self.client = client
        self.table_id = table_id
        self.dim = dim
        self.capacity = capacity
        self.rows = jnp.zeros((capacity, dim), jnp.float32)
        # vectorized control plane: sorted cached ids + aligned slots
        # (np.searchsorted membership), LRU as a per-slot stamp array —
        # steady-state cost is O(U log N) numpy, no per-id python loops
        self._ids = np.empty(0, np.int64)        # sorted cached ids
        self._slots = np.empty(0, np.int32)      # slot of self._ids[i]
        self._stamp = np.zeros(capacity, np.int64)
        self._tick = 0
        self.free = list(range(capacity))
        self.pull_rpcs = 0
        self.push_rpcs = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _lookup(self, uids):
        """(found mask, slot array — valid where found)."""
        if not len(self._ids):
            return (np.zeros(len(uids), bool),
                    np.full(len(uids), -1, np.int32))
        pos = np.searchsorted(self._ids, uids)
        pos_c = np.minimum(pos, len(self._ids) - 1)
        found = self._ids[pos_c] == uids
        slots = np.where(found, self._slots[pos_c], -1).astype(np.int32)
        return found, slots

    def ensure(self, uids):
        """Make every id in `uids` cached; returns their slot array.
        Misses are pulled in ONE rpc; evictions written back in ONE rpc."""
        uids = np.asarray(uids, np.int64)
        self._tick += 1
        found, slots = self._lookup(uids)
        n_miss_pos = int((~found).sum())
        self.hits += int(found.sum())
        if n_miss_pos:
            miss = np.unique(uids[~found])
            self.misses += len(miss)
            needed = len(miss) - len(self.free)
            if needed > 0:
                # evict the stalest slots not referenced by this batch
                batch_slots = set(int(s) for s in slots[found])
                order = np.argsort(self._stamp[self._slots])
                victims_idx = [int(i) for i in order
                               if int(self._slots[i]) not in batch_slots]
                if len(victims_idx) < needed:
                    raise RuntimeError(
                        f"HotRowCache: working set {len(uids)} exceeds "
                        f"capacity {self.capacity}")
                victims_idx = np.asarray(victims_idx[:needed])
                vids = self._ids[victims_idx]
                vslots = self._slots[victims_idx]
                self.client.set_sparse(
                    self.table_id, vids,
                    np.asarray(self.rows[jnp.asarray(vslots)]))
                self.push_rpcs += 1
                self.evictions += len(victims_idx)
                self.free.extend(int(s) for s in vslots)
                keep = np.ones(len(self._ids), bool)
                keep[victims_idx] = False
                self._ids = self._ids[keep]
                self._slots = self._slots[keep]
            pulled = self.client.pull_sparse(self.table_id, miss, self.dim)
            self.pull_rpcs += 1
            mslots = np.array([self.free.pop() for _ in miss], np.int32)
            self.rows = self.rows.at[jnp.asarray(mslots)].set(
                jnp.asarray(np.asarray(pulled, np.float32)))
            order = np.argsort(np.concatenate([self._ids, miss]))
            self._ids = np.concatenate([self._ids, miss])[order]
            self._slots = np.concatenate([self._slots, mslots])[order]
            _, slots = self._lookup(uids)
        self._stamp[slots] = self._tick
        return slots

    def flush(self):
        """Write ALL cached rows back (checkpoint/shutdown)."""
        if not len(self._ids):
            return
        self.client.set_sparse(
            self.table_id, self._ids,
            np.asarray(self.rows[jnp.asarray(self._slots)]))
        self.push_rpcs += 1

    def stats(self):
        return {"pull_rpcs": self.pull_rpcs, "push_rpcs": self.push_rpcs,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}


class HeterPSTrainer:
    """Device-resident dense tower + host-PS sparse embeddings.

    loss_fn(dense_params, urows, inv, *batch) -> scalar loss, where
    `urows[inv]` recovers per-position embedding rows ([B*S, emb_dim]).

    dense update runs on-device with `optimizer` (paddle_tpu Optimizer);
    sparse update is the PS table's optimizer (server-side SGD).
    """

    def __init__(self, loss_fn, dense_params, optimizer, client,
                 sparse_table=1, emb_dim=8, donate=True, cache_capacity=0,
                 sparse_lr=0.1):
        self.client = client
        self.sparse_table = sparse_table
        self.emb_dim = emb_dim
        self.optimizer = optimizer
        self.params = {n: jnp.asarray(a, jnp.float32)
                       for n, a in dense_params.items()}
        self.opt_state = optimizer.init_opt_state(self.params)
        self._step_i = 0
        apply_fn = optimizer.apply_gradients_fn()
        self.cache = (HotRowCache(client, sparse_table, emb_dim,
                                  cache_capacity)
                      if cache_capacity else None)

        def _step(params, opt_state, urows, inv, lr, step_i, *batch):
            loss, (gp, grows) = jax.value_and_grad(
                lambda p, r: loss_fn(p, r, inv, *batch),
                argnums=(0, 1))(params, urows)
            new_params, new_opt = apply_fn(params, gp, opt_state, lr, step_i)
            return loss, new_params, new_opt, grows

        donate_args = (0, 1) if donate else ()
        self._compiled = jax.jit(_step, donate_argnums=donate_args)

        def _step_cached(params, opt_state, cache_rows, slots, inv, lr,
                         step_i, *batch):
            # gather from the HBM-resident cache; the sparse SGD update
            # (same rule the server applies) runs on-device — no RPCs
            def f(p, rows):
                return loss_fn(p, rows[slots], inv, *batch)
            loss, (gp, grows_full) = jax.value_and_grad(
                f, argnums=(0, 1))(params, cache_rows)
            new_params, new_opt = apply_fn(params, gp, opt_state, lr, step_i)
            new_rows = cache_rows - jnp.asarray(sparse_lr, jnp.float32) \
                * grows_full
            return loss, new_params, new_opt, new_rows

        donate_c = (0, 1, 2) if donate else ()
        self._compiled_cached = jax.jit(_step_cached,
                                        donate_argnums=donate_c)

    def step(self, ids, *batch):
        """One heter step. `ids` is any int array of embedding ids for the
        batch; `urows[inv]` has one row per flattened id position. With a
        HotRowCache, repeated-key batches skip the host PS entirely."""
        c = self.client
        ids = np.asarray(ids).ravel()
        if ids.size == 0:
            raise ValueError("HeterPSTrainer.step: empty ids batch")
        uids, inv = np.unique(ids, return_inverse=True)
        b = _bucket(len(uids))
        pad = b - len(uids)
        uids_p = np.concatenate([uids, np.full(pad, uids[0], uids.dtype)]) \
            if pad else uids
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)

        if self.cache is not None:
            # padded duplicate slots get zero grad through the gather VJP
            # (inv never references the pad), so the scatter-add is exact
            slots = self.cache.ensure(uids_p)
            loss, self.params, self.opt_state, self.cache.rows = \
                self._compiled_cached(
                    self.params, self.opt_state, self.cache.rows,
                    jnp.asarray(slots), jnp.asarray(inv.astype(np.int32)),
                    lr, jnp.asarray(self._step_i, jnp.int32), *batch)
            return float(loss)

        urows = c.pull_sparse(self.sparse_table, uids_p, self.emb_dim)
        urows = np.asarray(urows, np.float32).reshape(b, self.emb_dim)
        loss, self.params, self.opt_state, grows = self._compiled(
            self.params, self.opt_state, jnp.asarray(urows),
            jnp.asarray(inv.astype(np.int32)), lr,
            jnp.asarray(self._step_i, jnp.int32), *batch)
        c.push_sparse_grad(self.sparse_table, uids_p, np.asarray(grows))
        return float(loss)

    def dense_state(self):
        """Host copies of the device-resident dense params."""
        return {n: np.asarray(a) for n, a in self.params.items()}
