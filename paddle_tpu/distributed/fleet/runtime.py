"""TheOnePSRuntime analog — DistributedStrategy -> parameter-server runtime
(ref python/paddle/distributed/fleet/runtime/the_one_ps.py TheOnePSRuntime:
strategy + role -> table configs -> server/worker bring-up).

What the reference does with proto table configs and brpc services, this
does directly against the native PS (native/src/ps_server.cc): the runtime
reads the strategy (a_sync / a_sync_configs / geo k_steps), derives the
table layout from the model's parameters (one dense table for the dense
pack, one sparse table per Embedding-like param), starts the server role
in-process, and hands workers a ready trainer (AsyncPSTrainer /
GeoPSTrainer) wired with registration + heartbeats."""
import numpy as np

from . import ps as ps_mod


class PSTableConfig:
    def __init__(self, table_id, kind, shape=None, dim=None, lr=0.1,
                 init_scale=0.01, name=""):
        self.table_id = table_id
        self.kind = kind            # "dense" | "sparse"
        self.shape = shape
        self.dim = dim
        self.lr = lr
        self.init_scale = init_scale
        self.name = name

    def __repr__(self):
        return (f"PSTableConfig({self.table_id}, {self.kind}, "
                f"name={self.name!r})")


def plan_tables(params, sparse_names=(), lr=0.1, emb_dim=None,
                init_scale=0.01):
    """Derive the table layout (ref the_one_ps.py _get_tables): params whose
    name matches `sparse_names` (or that look like embedding rows) become
    sparse tables; everything else packs into dense table 0."""
    dense, sparse = {}, []
    tid = 1
    configs = []
    for n, a in params.items():
        if n in sparse_names:
            arr = np.asarray(a)
            configs.append(PSTableConfig(tid, "sparse", dim=arr.shape[-1],
                                         lr=lr, init_scale=init_scale,
                                         name=n))
            tid += 1
        else:
            dense[n] = a
    total = int(sum(np.asarray(a).size for a in dense.values()))
    configs.insert(0, PSTableConfig(0, "dense", shape=(total,), lr=lr,
                                    name="dense_pack"))
    return configs, dense


class TheOnePSRuntime:
    """strategy + role -> running PS job half (server or worker).

    Usage (mirrors the reference's fleet.init + runtime._init_server/worker):

        runtime = TheOnePSRuntime(strategy, role="server"|"worker",
                                  endpoints=["127.0.0.1:0"])
        server = runtime.init_server(params, sparse_names=[...])  # blocks? no
        trainer = runtime.init_worker(loss_fn, params, worker_id=w, port=p)
    """

    def __init__(self, strategy=None, role="worker", lr=0.1,
                 heartbeat_timeout_s=10.0):
        self.strategy = strategy
        self.role = role
        self.lr = lr
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.geo_k = 0
        self.mode = "sync"
        if strategy is not None and getattr(strategy, "a_sync", False):
            cfg = getattr(strategy, "a_sync_configs", {}) or {}
            k = int(cfg.get("k_steps", 0) or 0)
            if k > 0:
                self.mode = "geo"
                self.geo_k = k
            else:
                self.mode = "async"
        self.server = None
        self.tables = None

    # ---------------------------------------------------------------- server
    def init_server(self, params, sparse_names=(), port=0, emb_dim=None,
                    init_scale=0.01):
        """Start the native server with tables derived from `params`.
        Returns (server, port)."""
        configs, dense = plan_tables(params, sparse_names, lr=self.lr,
                                     init_scale=init_scale)
        self.tables = configs
        srv = ps_mod.PsServer()
        for c in configs:
            if c.kind == "dense":
                srv.add_dense_table(c.table_id, int(np.prod(c.shape)),
                                    lr=c.lr)
            else:
                srv.add_sparse_table(c.table_id, c.dim, lr=c.lr,
                                     init_scale=c.init_scale)
        bound = srv.start(port)
        srv.set_heartbeat_timeout(self.heartbeat_timeout_s)
        self.server = srv
        return srv, bound

    # ---------------------------------------------------------------- worker
    def init_worker(self, loss_fn, params_template, worker_id, host="127.0.0.1",
                    port=None, emb_dim=8, init_dense=None):
        """Connect a worker: registers for liveness, starts its beat thread,
        and returns the trainer the strategy implies (async -> Hogwild,
        geo -> k-step delta pushing). The returned trainer grows
        `.stop_heartbeat()` and `.finish()` for clean teardown."""
        client = ps_mod.PsClient(host=host, port=port)
        cancel = client.start_heartbeat(worker_id,
                                        interval_s=min(
                                            1.0,
                                            self.heartbeat_timeout_s / 4))
        if init_dense is None:
            init_dense = worker_id == 0
        if self.mode == "geo":
            trainer = ps_mod.GeoPSTrainer(loss_fn, params_template, client,
                                          k_steps=self.geo_k, lr=self.lr,
                                          init_dense=init_dense)
        else:
            trainer = ps_mod.AsyncPSTrainer(loss_fn, params_template, client,
                                            emb_dim=emb_dim,
                                            init_dense=init_dense)
        trainer.worker_id = worker_id
        trainer.stop_heartbeat = cancel

        def finish():
            cancel()
            client.complete_worker(worker_id)
        trainer.finish = finish
        return trainer

    def stop(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
