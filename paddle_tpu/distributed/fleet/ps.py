"""Parameter-server runtime over the native C++ PS
(ref paddle/fluid/distributed/service/brpc_ps_server.h PsServer /
 brpc_ps_client.h PsClient, table/common_dense_table.h,
 table/common_sparse_table.h, fleet/runtime/the_one_ps.py TheOnePSRuntime,
 service/communicator.h async push semantics).

TPU-native split of responsibilities:
  - Servers (host-only processes) own tables: dense param blocks with
    server-side SGD apply (async/Hogwild) and sparse embedding tables with
    deterministic lazy row init.
  - Workers pull dense params + the batch's unique embedding rows, run the
    compiled TPU step (jax.value_and_grad over params AND rows), and push
    gradients back — the device never holds the full embedding table
    (host-offload for beyond-HBM sparse models, the heter-PS analog).
  - geo-SGD: workers train locally and push parameter deltas every k steps
    (PUSH_DENSE_DELTA), the geo_async mode of the reference communicator.
"""
import ctypes

import numpy as np
import jax

from ...utils.native_build import load_native

_f32p = ctypes.POINTER(ctypes.c_float)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _fptr(a):
    return a.ctypes.data_as(_f32p)


def _iptr(a):
    return a.ctypes.data_as(_i64p)


class PsServer:
    """In-process native PS server (one per server rank)."""

    def __init__(self):
        self._lib = load_native()
        self._h = self._lib.pt_ps_server_create()
        self.port = None

    def add_dense_table(self, table_id, size, lr=0.1, optimizer="sgd"):
        self._lib.pt_ps_add_dense_table(self._h, table_id, int(size),
                                        float(lr))
        self._set_optimizer(table_id, optimizer, is_sparse=False)

    def add_sparse_table(self, table_id, dim, lr=0.1, init_scale=0.01,
                         optimizer="sgd"):
        self._lib.pt_ps_add_sparse_table(self._h, table_id, int(dim),
                                         float(lr), float(init_scale))
        self._set_optimizer(table_id, optimizer, is_sparse=True)

    def _set_optimizer(self, table_id, optimizer, is_sparse):
        """Server-side update rule (ref ps/table/sparse_sgd_rule.cc:
        SparseNaiveSGDRule / SparseAdaGradSGDRule)."""
        if optimizer == "sgd":
            return
        if optimizer != "adagrad":
            raise ValueError(f"unknown PS table optimizer {optimizer!r} "
                             "(sgd | adagrad)")
        rc = self._lib.pt_ps_table_set_adagrad(self._h, table_id,
                                               int(is_sparse), 1e-6)
        if rc != 0:
            raise RuntimeError(f"no such table {table_id}")

    def start(self, port=0):
        p = self._lib.pt_ps_server_start(self._h, int(port))
        if p < 0:
            raise RuntimeError(f"ps server failed to bind port {port}")
        self.port = p
        return p

    def set_heartbeat_timeout(self, seconds):
        """RUNNING workers silent for longer are declared DEAD and evicted
        from barriers (ref heart_beat_monitor.h)."""
        self._lib.pt_ps_server_set_heartbeat_timeout(self._h,
                                                     int(seconds * 1000))

    def stop(self):
        if self._h:
            self._lib.pt_ps_server_stop(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pt_ps_server_stop(self._h)
                self._lib.pt_ps_server_destroy(self._h)
                self._h = None
        # interpreter teardown: ctypes globals may already be None'd, so
        # ANY exception type here is shutdown noise, not a real failure
        except Exception:   # ptlint: disable=swallowed-exception
            pass


class PsClient:
    """Worker-side connection to one PS server."""

    def __init__(self, host="127.0.0.1", port=None):
        self._lib = load_native()
        self._host, self._port = host, int(port)
        self._h = self._lib.pt_ps_client_create()
        if self._lib.pt_ps_client_connect(self._h, host.encode(),
                                          int(port)) != 0:
            raise ConnectionError(f"cannot connect to ps {host}:{port}")

    def pull_dense(self, table_id, size):
        out = np.empty(size, np.float32)
        self._check(self._lib.pt_ps_pull_dense(self._h, table_id, _fptr(out),
                                               size), "pull_dense")
        return out

    def push_dense_grad(self, table_id, grad):
        grad = np.ascontiguousarray(grad, np.float32)
        self._check(self._lib.pt_ps_push_dense(self._h, table_id,
                                               _fptr(grad), grad.size, 0),
                    "push_dense_grad")

    def push_dense_delta(self, table_id, delta):
        delta = np.ascontiguousarray(delta, np.float32)
        self._check(self._lib.pt_ps_push_dense(self._h, table_id,
                                               _fptr(delta), delta.size, 1),
                    "push_dense_delta")

    def set_dense(self, table_id, values):
        values = np.ascontiguousarray(values, np.float32)
        self._check(self._lib.pt_ps_push_dense(self._h, table_id,
                                               _fptr(values), values.size, 2),
                    "set_dense")

    def pull_sparse(self, table_id, ids, dim):
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty((ids.size, dim), np.float32)
        self._check(self._lib.pt_ps_pull_sparse(self._h, table_id, _iptr(ids),
                                                ids.size, _fptr(out), dim),
                    "pull_sparse")
        return out

    def push_sparse_grad(self, table_id, ids, grads):
        ids = np.ascontiguousarray(ids, np.int64)
        grads = np.ascontiguousarray(grads, np.float32)
        assert grads.shape[0] == ids.size
        self._check(self._lib.pt_ps_push_sparse_grad(
            self._h, table_id, _iptr(ids), ids.size, _fptr(grads),
            grads.shape[1]), "push_sparse_grad")

    def set_sparse(self, table_id, ids, values):
        """Absolute row overwrite (heter cache write-back, ckpt load)."""
        ids = np.ascontiguousarray(ids, np.int64)
        values = np.ascontiguousarray(values, np.float32)
        assert values.shape[0] == ids.size
        self._check(self._lib.pt_ps_set_sparse(
            self._h, table_id, _iptr(ids), ids.size, _fptr(values),
            values.shape[1]), "set_sparse")

    # ---- graph service (ref graph_py_service.h client surface)
    def add_edges(self, table_id, src, dst):
        pairs = np.ascontiguousarray(
            np.stack([np.asarray(src, np.int64).ravel(),
                      np.asarray(dst, np.int64).ravel()], axis=1))
        self._check(self._lib.pt_ps_add_edges(
            self._h, table_id, _iptr(pairs), pairs.shape[0]), "add_edges")

    def sample_neighbors(self, table_id, ids, k):
        """[n] ids -> [n, k] sampled neighbor ids (-1 pads isolated
        nodes): static shapes for the TPU consumer."""
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty((ids.size, int(k)), np.int64)
        self._check(self._lib.pt_ps_sample_neighbors(
            self._h, table_id, _iptr(ids), ids.size, int(k), _iptr(out)),
            "sample_neighbors")
        return out

    def node_degree(self, table_id, ids):
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty(ids.size, np.int64)
        self._check(self._lib.pt_ps_get_degree(
            self._h, table_id, _iptr(ids), ids.size, _iptr(out)),
            "node_degree")
        return out

    def random_nodes(self, table_id, n):
        out = np.empty(int(n), np.int64)
        self._check(self._lib.pt_ps_random_nodes(
            self._h, table_id, int(n), _iptr(out)), "random_nodes")
        return out

    def barrier(self, world_size, worker_id=None):
        """True = clean release; False = released degraded (the server's
        heartbeat monitor evicted dead workers from the cohort instead of
        letting the barrier hang — ref heart_beat_monitor.h:51). Pass
        worker_id when workers register/heartbeat: arrivals are then tracked
        per worker, so a dead worker's stale arrival can't fake quorum."""
        if worker_id is None:
            rc = self._lib.pt_ps_barrier(self._h, int(world_size))
        else:
            rc = self._lib.pt_ps_barrier_as(self._h, int(world_size),
                                            int(worker_id))
        if rc < 0:
            raise RuntimeError(f"ps client barrier failed (rc={rc})")
        return rc == 1

    # ------------------------------------------------------ worker liveness
    def register_worker(self, worker_id):
        self._check(self._lib.pt_ps_worker_register(self._h, int(worker_id)),
                    "register_worker")

    def heartbeat(self, worker_id):
        """One beat. 1 = accepted, 0 = worker COMPLETED (stop beating),
        -1 = transport failure (transient: the next beat re-dials and the
        server re-registers a beating worker after restart)."""
        return int(self._lib.pt_ps_worker_heartbeat(self._h, int(worker_id)))

    def complete_worker(self, worker_id):
        self._check(self._lib.pt_ps_worker_complete(self._h, int(worker_id)),
                    "complete_worker")

    def query_workers(self):
        """(running, completed, dead) counts from the server's monitor."""
        out = np.zeros(3, np.uint32)
        self._check(self._lib.pt_ps_query_workers(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))),
            "query_workers")
        return int(out[0]), int(out[1]), int(out[2])

    def start_heartbeat(self, worker_id, interval_s=1.0):
        """Background beat thread on its OWN connection — a blocking
        barrier on this client must not starve the beats it exists to
        protect (the reference's worker heartbeat thread is likewise a
        separate brpc channel)."""
        import threading
        stop = threading.Event()
        beat_client = PsClient(host=self._host, port=self._port)
        self.register_worker(worker_id)

        def loop():
            while not stop.wait(interval_s):
                try:
                    if beat_client.heartbeat(worker_id) == 0:
                        return          # COMPLETED: beats are over
                except RuntimeError:
                    pass                # transient transport error: retry

        t = threading.Thread(target=loop, daemon=True)
        t.start()

        def cancel():
            stop.set()
            t.join(timeout=5)
        return cancel

    def save(self, table_id, path):
        self._check(self._lib.pt_ps_save(self._h, table_id,
                                         str(path).encode()), "save")

    def load(self, table_id, path):
        self._check(self._lib.pt_ps_load(self._h, table_id,
                                         str(path).encode()), "load")

    def _check(self, rc, what):
        if rc != 0:
            raise RuntimeError(f"ps client {what} failed (rc={rc})")

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pt_ps_client_destroy(self._h)
                self._h = None
        # interpreter teardown: ctypes globals may already be None'd, so
        # ANY exception type here is shutdown noise, not a real failure
        except Exception:   # ptlint: disable=swallowed-exception
            pass


# --------------------------------------------------------------------------
# worker-side trainers
# --------------------------------------------------------------------------

class _ParamCodec:
    """flatten/unflatten a name->array dict into one dense-table vector."""

    def __init__(self, template):
        self.names = sorted(template)
        self.shapes = {n: np.asarray(template[n]).shape for n in self.names}
        self.sizes = {n: int(np.prod(self.shapes[n])) for n in self.names}
        self.total = sum(self.sizes.values())

    def flatten(self, params):
        return np.concatenate(
            [np.asarray(params[n], np.float32).ravel() for n in self.names])

    def unflatten(self, flat):
        out, off = {}, 0
        for n in self.names:
            k = self.sizes[n]
            out[n] = np.asarray(flat[off:off + k],
                                np.float32).reshape(self.shapes[n])
            off += k
        return out


class AsyncPSTrainer:
    """Async (a_sync/Hogwild) PS worker loop (ref
    parameter_server_optimizer a_sync mode + HogwildWorker::TrainFiles).

    loss_fn(params, urows, inv, *batch) -> scalar jnp loss, where
    `urows[inv]` recovers per-position embedding rows. Gradients w.r.t.
    duplicate ids are accumulated by autodiff through the gather.
    """

    def __init__(self, loss_fn, params_template, client, dense_table=0,
                 sparse_table=1, emb_dim=8, init_dense=True):
        self.client = client
        self.dense_table = dense_table
        self.sparse_table = sparse_table
        self.emb_dim = emb_dim
        self.codec = _ParamCodec(params_template)
        if init_dense:
            client.set_dense(dense_table, self.codec.flatten(params_template))
        self._grad = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))

    def step(self, ids, *batch):
        """One async step: pull, compute, push. Returns host loss."""
        c = self.client
        params = self.codec.unflatten(
            c.pull_dense(self.dense_table, self.codec.total))
        ids = np.asarray(ids).ravel()
        uids, inv = np.unique(ids, return_inverse=True)
        urows = c.pull_sparse(self.sparse_table, uids, self.emb_dim)
        loss, (gp, grows) = self._grad(params, urows, inv.astype(np.int32),
                                       *batch)
        c.push_dense_grad(self.dense_table, self.codec.flatten(gp))
        c.push_sparse_grad(self.sparse_table, uids, np.asarray(grows))
        return float(loss)


class GeoPSTrainer:
    """geo-SGD worker (ref communicator geo mode / geo_sgd_transpiler):
    trains a local copy, pushes the parameter DELTA every k steps and
    re-pulls — communication-reducing async DP for PS mode."""

    def __init__(self, loss_fn, params_template, client, dense_table=0,
                 k_steps=4, lr=0.1, init_dense=True):
        self.client = client
        self.dense_table = dense_table
        self.k_steps = k_steps
        self.lr = lr
        self.codec = _ParamCodec(params_template)
        if init_dense:
            client.set_dense(dense_table, self.codec.flatten(params_template))
        self._base = client.pull_dense(dense_table, self.codec.total)
        self._local = self._base.copy()
        self._i = 0
        self._grad = jax.jit(jax.value_and_grad(loss_fn))

    def step(self, *batch):
        params = self.codec.unflatten(self._local)
        loss, gp = self._grad(params, *batch)
        self._local -= self.lr * self.codec.flatten(gp)
        self._i += 1
        if self._i % self.k_steps == 0:
            delta = self._local - self._base
            self.client.push_dense_delta(self.dense_table, delta)
            self._base = self.client.pull_dense(self.dense_table,
                                                self.codec.total)
            self._local = self._base.copy()
        return float(loss)
