"""Distributed metric aggregation (ref fleet/metrics/metric.py — sum/max/
min/auc/rmse aggregated across workers over gloo/fleet util).

Each helper takes a local metric value (array or scalar) and returns the
global aggregate using the fleet util host collective (single-process:
identity). AUC aggregates the positive/negative histogram buckets, NOT
the local AUCs — same math as the reference's global_auc."""
import numpy as np


def _util():
    from .base import _fleet
    return _fleet.util


def sum(value, comm_world="worker"):  # noqa: A001 - paddle api name
    return _util().all_reduce(np.asarray(value, np.float64), "sum",
                              comm_world)


def max(value, comm_world="worker"):  # noqa: A001
    return _util().all_reduce(np.asarray(value, np.float64), "max",
                              comm_world)


def min(value, comm_world="worker"):  # noqa: A001
    return _util().all_reduce(np.asarray(value, np.float64), "min",
                              comm_world)


def mean(value, count, comm_world="worker"):
    """Global weighted mean from (local sum, local count)."""
    tot = _util().all_reduce(np.asarray([value, count], np.float64),
                             "sum", comm_world)
    return float(tot[0]) / np.maximum(float(tot[1]), 1e-12)


def acc(correct, total, comm_world="worker"):
    return mean(correct, total, comm_world)


def rmse(sq_err_sum, count, comm_world="worker"):
    return float(np.sqrt(mean(sq_err_sum, count, comm_world)))


def mae(abs_err_sum, count, comm_world="worker"):
    return mean(abs_err_sum, count, comm_world)


def auc(pos_bins, neg_bins, comm_world="worker"):
    """Global AUC from per-worker score histograms: pos_bins[i]/neg_bins[i]
    count positives/negatives whose score fell in bucket i (ascending
    score). Aggregate the histograms, then trapezoid over the ROC."""
    pos = np.asarray(_util().all_reduce(
        np.asarray(pos_bins, np.float64), "sum", comm_world))
    neg = np.asarray(_util().all_reduce(
        np.asarray(neg_bins, np.float64), "sum", comm_world))
    # descending score order for cumulative TP/FP
    tp = np.cumsum(pos[::-1])
    fp = np.cumsum(neg[::-1])
    tot_p, tot_n = tp[-1], fp[-1]
    if tot_p == 0 or tot_n == 0:
        return 0.5
    tpr = np.concatenate([[0.0], tp / tot_p])
    fpr = np.concatenate([[0.0], fp / tot_n])
    return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
        else float(np.trapz(tpr, fpr))
