"""fleet.utils (ref python/paddle/distributed/fleet/utils): filesystem
clients + recompute re-export."""
from .fs import LocalFS, HDFSClient, FSFileExistsError, FSFileNotExistsError

from ....incubate.recompute import recompute  # noqa: F401

__all__ = ["LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError", "recompute"]
