"""Filesystem clients (ref python/paddle/distributed/fleet/utils/fs.py:
LocalFS + HDFSClient over the hadoop CLI). The PS runtime and
auto-checkpoint use these to move table snapshots/checkpoints."""
import os
import shutil


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """ref fs.py LocalFS — same call surface."""

    def ls_dir(self, fs_path):
        """Returns (dirs, files) directly under fs_path."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def mv(self, src_path, dst_path, overwrite=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            # file-over-file rides os.replace (atomic: checkpoint rotation
            # never has a window with no checkpoint on disk); any other
            # type combination needs dst pre-deleted first
            if os.path.isdir(dst_path) or os.path.isdir(src_path):
                self.delete(dst_path)
        os.replace(src_path, dst_path)

    rename = mv

    def upload(self, local_path, fs_path):
        """LocalFS 'upload' is a copy (parity with the HDFS surface)."""
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """ref fs.py HDFSClient: the reference shells out to the hadoop CLI.
    This build does not implement the CLI bridge — construction always
    raises with guidance (an importable stub that constructed and then
    crashed per-method would be worse). LocalFS exposes the same call
    surface for local/shared-filesystem storage."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        raise RuntimeError(
            "HDFSClient (hadoop CLI bridge) is not implemented in "
            "paddle_tpu; use fleet.utils.LocalFS on a local or shared "
            "(NFS) filesystem — the call surface is identical")
