"""Fleet facade (ref python/paddle/distributed/fleet/base/fleet_base.py:63).
Full strategy-compiler stack lands with the hybrid-parallel milestone; the
facade keeps the reference call contract: init / distributed_optimizer /
distributed_model / minimize."""
from .base import (init, is_first_worker, worker_index, worker_num,
                   is_worker, worker_endpoints, server_num, server_index,
                   server_endpoints, is_server, barrier_worker,
                   distributed_optimizer, distributed_model,
                   DistributedStrategy, UserDefinedRoleMaker,
                   PaddleCloudRoleMaker, UtilBase, fleet, build_train_step,
                   init_server, run_server, init_worker, stop_worker,
                   minimize, step, clear_grad, get_lr, set_lr, state_dict,
                   set_state_dict, amp_init, shrink, save_persistables,
                   save_inference_model)


from .trainers import MultiTrainer, DistMultiTrainer


def __getattr__(name):
    # native PS runtime loads (and builds) the C++ library on first use
    if name in ("PsServer", "PsClient", "AsyncPSTrainer", "GeoPSTrainer"):
        from . import ps
        return getattr(ps, name)
    if name == "HeterPSTrainer":
        from .heter import HeterPSTrainer
        return HeterPSTrainer
    if name == "TheOnePSRuntime":
        from .runtime import TheOnePSRuntime
        return TheOnePSRuntime
    if name == "util":
        # ref fleet_base.py `util` property: host-collective helpers
        from .base import _fleet
        return _fleet.util
    if name in ("metrics", "utils"):
        import importlib
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
