"""Trainer/worker family (ref paddle/fluid/framework/multi_trainer.cc
MultiTrainer + hogwild_worker.cc, dist_multi_trainer.cc DistMultiTrainer,
trainer_factory.py).

TPU-native redesign: the reference runs N CPU threads each interpreting the
program over its own data-feed channel (Hogwild on shared host params). On
TPU the device executes one compiled step at a time, so thread-parallelism
belongs to the HOST side of the pipeline: MultiTrainer runs N feed threads
that pull+collate batches from the dataset (the DataFeed channel analog)
into a bounded queue, while one consumer drives the compiled train step —
host preprocessing overlaps device compute, which is what the reference's
thread pool actually buys on its hardware. DistMultiTrainer composes the
same pump with PS workers (each feed thread owns an Async/Geo PS trainer —
that IS Hogwild, server-side)."""
import queue
import threading

import numpy as np


class MultiTrainer:
    """N feed threads -> bounded batch queue -> one step consumer
    (ref multi_trainer.cc run + trainer_desc thread_num).

    train_fn(*batch_arrays) -> loss-like (host float or array).
    dataset: iterable of batches (io.DatasetBase / DataLoader / generator
    factory called per epoch).
    """

    def __init__(self, train_fn, num_threads=2, queue_depth=8):
        self.train_fn = train_fn
        self.num_threads = max(1, int(num_threads))
        self.queue_depth = queue_depth

    def train_from_dataset(self, dataset, epochs=1):
        """Returns per-epoch mean losses. Feed threads shard the dataset
        round-robin (channel semantics); the consumer drains in arrival
        order (Hogwild: no ordering guarantee, like the reference).

        dataset may be a list, a re-iterable, a one-shot iterator (drained
        once, reused across epochs), or a zero-arg factory called per
        epoch."""
        losses = []
        materialized = None
        for _ in range(epochs):
            if callable(dataset):
                batches = list(dataset())
            else:
                if materialized is None:
                    materialized = list(dataset)
                batches = materialized
            if not batches:
                raise ValueError("MultiTrainer: dataset produced no batches")
            losses.append(self._one_epoch(batches))
        return losses

    def _one_epoch(self, batches):
        q = queue.Queue(maxsize=self.queue_depth)
        n = self.num_threads
        done = object()
        cancel = threading.Event()
        errors = []

        def feeder(tid):
            try:
                for b in batches[tid::n]:
                    while not cancel.is_set():
                        try:
                            q.put(tuple(np.asarray(a) for a in b),
                                  timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if cancel.is_set():
                        return
            except BaseException as e:    # surfaced by the consumer
                errors.append(e)
            finally:
                # the done marker must arrive unless the epoch was cancelled
                # (a dropped marker deadlocks the consumer)
                while not cancel.is_set():
                    try:
                        q.put(done, timeout=0.2)
                        break
                    except queue.Full:
                        continue

        threads = [threading.Thread(target=feeder, args=(t,), daemon=True)
                   for t in range(n)]
        for t in threads:
            t.start()
        total, count, finished = 0.0, 0, 0
        try:
            while finished < n:
                item = q.get()
                if item is done:
                    finished += 1
                    continue
                out = self.train_fn(*item)
                total += float(np.asarray(out).ravel()[0]) \
                    if out is not None else 0.0
                count += 1
        finally:
            # unblock any feeder parked on a full queue before propagating
            cancel.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            for t in threads:
                t.join(timeout=10)
        if errors:
            raise errors[0]
        return total / max(count, 1)


class DistMultiTrainer:
    """PS-mode thread family (ref dist_multi_trainer.cc + downpour_worker):
    each thread owns a PS trainer (Hogwild against the server's tables) and
    a shard of the dataset."""

    def __init__(self, make_worker, num_threads=2):
        """make_worker(thread_id) -> object with .step(*batch)."""
        self.make_worker = make_worker
        self.num_threads = max(1, int(num_threads))

    def train_from_dataset(self, dataset, epochs=1):
        batches = list(dataset)
        n = self.num_threads
        results = [None] * n
        errors = []

        def run(tid):
            try:
                worker = self.make_worker(tid)
                losses = []
                for _ in range(epochs):
                    for b in batches[tid::n]:
                        losses.append(worker.step(*b))
                if hasattr(worker, "finish"):
                    worker.finish()
                results[tid] = losses
            except BaseException as e:   # re-raised in the caller
                errors.append((tid, e))

        threads = [threading.Thread(target=run, args=(t,)) for t in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            tid, e = errors[0]
            raise RuntimeError(
                f"DistMultiTrainer worker thread {tid} failed") from e
        return results
