"""Fleet base: role makers, DistributedStrategy, facade
(ref fleet/base/fleet_base.py:63,130,598,643; base/role_maker.py:528;
base/distributed_strategy.py + framework/distributed_strategy.proto:122).

DistributedStrategy keeps the reference's strategy-bag surface (amp, recompute,
sharding, pipeline, tensor_parallel...); the strategy compiler maps enabled
features onto mesh axes + jax transforms instead of program rewrites
(see meta_optimizers.py).
"""
import os

from ..env import ParallelEnv, get_rank, get_world_size
from .. import mesh as mesh_mod


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints


class UserDefinedRoleMaker(RoleMakerBase):
    """ref role_maker.py:875 — explicit cluster spec for tests."""

    def __init__(self, is_collective=False, current_id=0, role=Role.WORKER,
                 worker_num=0, worker_endpoints=None, server_endpoints=None,
                 **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = worker_endpoints or \
            [f"127.0.0.1:{36000 + i}" for i in range(worker_num)]
        self._server_endpoints = server_endpoints or []


class PaddleCloudRoleMaker(RoleMakerBase):
    """ref role_maker.py:861 — parse PADDLE_* env."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        env = ParallelEnv()
        self._current_id = env.rank
        self._worker_endpoints = env.trainer_endpoints or ["127.0.0.1:36000"]
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        if training_role == "PSERVER":
            self._role = Role.SERVER
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = eps.split(",") if eps else []
            self._current_id = int(os.environ.get("PADDLE_PSERVER_ID", 0))
        else:
            self._role = Role.WORKER
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = eps.split(",") if eps else []


class DistributedStrategy:
    """ref distributed_strategy.proto:122 — feature-flag bag + config dicts."""

    def __init__(self):
        # collective features
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_bf16": False}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs = {"fuse_broadcast_MB": 32,
                                 "sharding_degree": 1}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1, "sp_degree": 1}
        self.lamb = False
        self.lamb_configs = {}
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.999]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = {"init_k_steps": 1,
                                          "begin_step": 1}
        self.fp16_allreduce = False
        self.fp16_allreduce_configs = {"dtype": "float16"}
        self.find_unused_parameters = False
        # async PS
        self.a_sync = False
        self.a_sync_configs = {"k_steps": 0, "launch_barrier": True}
        # misc mirrors
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1
        self.auto = False
        self.elastic = False
        self.build_strategy = None
        self.execution_strategy = None

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"


class UtilBase:
    """ref fleet/utils/fleet_util.py + base/util_factory.py — host-side
    collectives delegate to the gloo-analog kv-store collective when the
    launcher set one up (distributed/gloo.py), single-process fallback
    otherwise."""

    def _host(self):
        if not hasattr(self, "_host_coll"):
            from ..gloo import collective_from_env
            self._host_coll = collective_from_env()
        return self._host_coll

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        hc = self._host()
        if hc is None:
            return input
        import numpy as np
        out = hc.all_reduce(np.asarray(input), op=mode)
        return out if hasattr(input, "shape") else type(input)(out)

    def barrier(self, comm_world="worker"):
        hc = self._host()
        if hc is not None:
            hc.barrier()
            return
        from ..collective import barrier as _barrier
        _barrier()

    def all_gather(self, input, comm_world="worker"):
        hc = self._host()
        if hc is None:
            return [input]
        import json as _json
        parts = hc.all_gather(_json.dumps(input).encode())
        return [_json.loads(p) for p in parts]

    def print_on_rank(self, message, rank_id=0):
        """ref util_factory.py print_on_rank."""
        if worker_index() == rank_id:
            print(message, flush=True)

    def get_file_shard(self, files):
        idx = worker_index()
        n = worker_num()
        return [f for i, f in enumerate(files) if i % n == idx]


class _FleetState:
    def __init__(self):
        self.role_maker = None
        self.strategy = None
        self.initialized = False
        self.util = UtilBase()


_fleet = _FleetState()


def init(role_maker=None, is_collective=False, strategy=None):
    """ref fleet_base.py:130."""
    _fleet.role_maker = role_maker or PaddleCloudRoleMaker(
        is_collective=is_collective)
    _fleet.strategy = strategy or DistributedStrategy()
    _fleet.initialized = True
    # a re-init starts a fresh job: stale PS/optimizer handles from the
    # previous one must not leak into it
    _fleet.latest_opt = None
    _fleet.runtime = None
    _fleet.server = None
    _fleet.server_port = None
    _fleet.worker_trainer = None
    # build the mesh implied by hybrid_configs; strategy.tensor_parallel
    # (ref distributed_strategy.py tensor_parallel + configs) is the
    # non-hybrid spelling of an mp degree
    hc = dict(_fleet.strategy.hybrid_configs)
    if getattr(_fleet.strategy, "tensor_parallel", False):
        tp = int(getattr(_fleet.strategy, "tensor_parallel_configs", {})
                 .get("tensor_parallel_degree", 1) or 1)
        if tp > 1 and int(hc.get("mp_degree", 1) or 1) <= 1:
            hc["mp_degree"] = tp
    import jax
    ndev = len(jax.devices())
    axes = {}
    for key, name in (("dp_degree", mesh_mod.DP_AXIS),
                      ("pp_degree", mesh_mod.PP_AXIS),
                      ("sharding_degree", "sharding"),
                      ("mp_degree", mesh_mod.MP_AXIS),
                      ("sp_degree", mesh_mod.SP_AXIS)):
        d = int(hc.get(key, 1) or 1)
        if d > 1:
            axes[name] = d
    if axes:
        total = 1
        for v in axes.values():
            total *= v
        if total <= ndev:
            mesh_mod.make_mesh(axes)
    else:
        mesh_mod.default_mesh()
    return _fleet


def is_first_worker():
    return _fleet.role_maker is None or _fleet.role_maker.is_first_worker()


def worker_index():
    return _fleet.role_maker.worker_index() if _fleet.role_maker else get_rank()


def worker_num():
    return _fleet.role_maker.worker_num() if _fleet.role_maker \
        else get_world_size()


def is_worker():
    return _fleet.role_maker is None or _fleet.role_maker.is_worker()


def worker_endpoints(to_string=False):
    eps = _fleet.role_maker.get_trainer_endpoints() if _fleet.role_maker else []
    return ",".join(eps) if to_string else eps


def server_num():
    return _fleet.role_maker.server_num() if _fleet.role_maker else 0


def server_index():
    return _fleet.role_maker.server_index() if _fleet.role_maker else 0


def server_endpoints(to_string=False):
    eps = _fleet.role_maker.get_pserver_endpoints() if _fleet.role_maker else []
    return ",".join(eps) if to_string else eps


def is_server():
    return _fleet.role_maker is not None and _fleet.role_maker.is_server()


def barrier_worker():
    from ..collective import barrier
    barrier()


def distributed_model(model):
    """ref fleet_base.py:643 — wrap for data parallelism."""
    from ..parallel import DataParallel
    if isinstance(model, DataParallel):
        return model
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """ref fleet_base.py:598 — returns a meta-optimizer chain honoring the
    strategy (meta_optimizers.py)."""
    from .meta_optimizers import build_distributed_optimizer
    strat = strategy or _fleet.strategy or DistributedStrategy()
    _fleet.strategy = strat
    _fleet.latest_opt = build_distributed_optimizer(optimizer, strat)
    return _fleet.latest_opt


def build_train_step(model, loss_fn, optimizer, **kwargs):
    """Strategy -> execution: pick + configure the compiled train step the
    meta-optimizer chain implies. This is where the reference applies its
    program rewrites (ref fleet_base.py:1070 minimize -> strategy_compiler
    -> meta-optimizer .minimize_impl chain); here the transforms dict
    recorded by meta_optimizers.py selects/teaches ONE jitted step:
      pipeline   -> PipelineTrainStep over the 'pp' mesh axis
      localsgd   -> LocalSGDTrainStep (per-replica params, periodic sync)
      mesh>1 dev -> ShardedTrainStep (GSPMD; amp/recompute/sharding/
                    gradient_merge consumed in-step via jit/transforms.py)
      otherwise  -> single-chip TrainStep (same transforms)."""
    from ...jit import TrainStep, transforms as tfm
    from ..parallel import DataParallel
    if isinstance(model, DataParallel):
        model = model._layers
    tf = tfm.resolve(optimizer)
    mesh = mesh_mod.get_mesh()
    ndev = len(mesh.devices.flat) if mesh is not None else 1
    # pipeline/localsgd steps don't expose per-batch outputs (micro-batched
    # / per-replica); TrainStep and ShardedTrainStep do
    ro = bool(kwargs.pop("return_outputs", False))
    if tf.get("pipeline") is not None and mesh is not None and \
            mesh_mod.PP_AXIS in mesh.axis_names:
        from ..pipeline import PipelineTrainStep
        cfg = tf["pipeline"]
        return PipelineTrainStep(
            model, loss_fn, optimizer,
            num_micro=max(1, int(cfg.get("accumulate_steps", 1) or 1)),
            **kwargs)
    if tf.get("dgc") is not None and mesh is not None and ndev > 1:
        from ..dgc import DGCTrainStep
        cfg = tf["dgc"]
        return DGCTrainStep(
            model, loss_fn, optimizer, sparsity=cfg.get("sparsity", 0.999),
            rampup_begin_step=cfg.get("rampup_begin_step", 0), **kwargs)
    if tf.get("localsgd") is not None and mesh is not None and ndev > 1:
        from ..localsgd import LocalSGDTrainStep
        cfg = tf["localsgd"]
        return LocalSGDTrainStep(
            model, loss_fn, optimizer,
            k_steps=max(1, int(cfg.get("k_steps", 1) or 1)),
            adaptive=bool(cfg.get("adaptive", False)),
            init_k_steps=int(cfg.get("init_k_steps", 1) or 1),
            begin_step=int(cfg.get("begin_step", 1) or 1), **kwargs)
    if mesh is not None and ndev > 1:
        from ..sharded import ShardedTrainStep
        return ShardedTrainStep(model, loss_fn, optimizer,
                                return_outputs=ro, **kwargs)
    return TrainStep(model, loss_fn, optimizer, return_outputs=ro,
                     **kwargs)


class _FleetModule:
    """Attribute-style facade: fleet.init(...), fleet.worker_num()..."""
    init = staticmethod(init)
    is_first_worker = staticmethod(is_first_worker)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_worker = staticmethod(is_worker)
    worker_endpoints = staticmethod(worker_endpoints)
    server_num = staticmethod(server_num)
    server_index = staticmethod(server_index)
    server_endpoints = staticmethod(server_endpoints)
    is_server = staticmethod(is_server)
    barrier_worker = staticmethod(barrier_worker)
    distributed_optimizer = staticmethod(distributed_optimizer)
    distributed_model = staticmethod(distributed_model)
    build_train_step = staticmethod(build_train_step)

    @property
    def util(self):
        return _fleet.util


fleet = _FleetModule()


# --------------------------------------------------------------------------
# PS lifecycle + optimizer delegation on the facade (ref fleet_base.py:
# init_server/run_server/init_worker/stop_worker + the Fleet object's
# minimize/step/clear_grad/get_lr/set_lr/state_dict passthroughs)
# --------------------------------------------------------------------------

def init_server(params=None, sparse_names=(), port=0, lr=0.1, **kwargs):
    """Start the native PS with tables planned from `params` (ref
    fleet_base.py init_server; table planning = the reference's
    program-derived table config). Returns the bound port. Extra kwargs
    (emb_dim, init_scale) forward to the runtime's table planner."""
    from .runtime import TheOnePSRuntime
    _fleet.runtime = TheOnePSRuntime(_fleet.strategy, role="server", lr=lr)
    _fleet.server, bound = _fleet.runtime.init_server(
        params or {}, sparse_names, port=port, **kwargs)
    _fleet.server_port = bound
    return bound


def run_server(block=True, poll_s=0.5):
    """ref fleet_base.py run_server: serve until stop_worker()/stop() —
    the reference blocks the server process the same way."""
    import time
    if getattr(_fleet, "runtime", None) is None or _fleet.server is None:
        raise RuntimeError("fleet.run_server: call fleet.init_server first")
    while block and _fleet.runtime.server is not None:
        time.sleep(poll_s)
    return _fleet.server_port


def init_worker(loss_fn=None, params=None, worker_id=None, host="127.0.0.1",
                port=None, **kwargs):
    """Connect this worker to the PS: liveness registration + heartbeat +
    the trainer the strategy implies (async->Hogwild, geo->k-step deltas).
    Returns the trainer (ref fleet_base.py init_worker)."""
    from .runtime import TheOnePSRuntime
    rt = TheOnePSRuntime(_fleet.strategy, role="worker")
    wid = worker_id if worker_id is not None else worker_index()
    trainer = rt.init_worker(loss_fn, params or {}, wid, host=host,
                             port=port, **kwargs)
    _fleet.worker_trainer = trainer
    return trainer


def stop_worker():
    """ref fleet_base.py stop_worker: clean COMPLETE + heartbeat cancel on
    a worker; server-side tears the server down."""
    tr = getattr(_fleet, "worker_trainer", None)
    if tr is not None:
        tr.finish()
        _fleet.worker_trainer = None
    rt = getattr(_fleet, "runtime", None)
    if rt is not None:
        rt.stop()
        _fleet.server = None
        _fleet.server_port = None


def shrink(threshold=None):
    raise NotImplementedError(
        "fleet.shrink: sparse-table eviction by staleness is not "
        "implemented (the native SparseTable does not track per-row "
        "access times); delete-and-reload via save/load instead")


def _last_opt():
    opt = getattr(_fleet, "latest_opt", None)
    # (init() resets this to None on re-init — stale handles never leak)
    if opt is None:
        raise RuntimeError(
            "no distributed optimizer yet — call "
            "fleet.distributed_optimizer(opt) first")
    return opt


def minimize(loss, startup_program=None, parameter_list=None,
             no_grad_set=None):
    return _last_opt().minimize(loss, startup_program, parameter_list,
                                no_grad_set)


def step():
    return _last_opt().inner_opt.step()


def clear_grad():
    return _last_opt().inner_opt.clear_grad()


def get_lr():
    return _last_opt().inner_opt.get_lr()


def set_lr(value):
    return _last_opt().inner_opt.set_lr(value)


def state_dict():
    return _last_opt().inner_opt.state_dict()


def set_state_dict(state):
    return _last_opt().inner_opt.set_state_dict(state)


def amp_init(place=None, scope=None, test_program=None, use_fp16_test=False):
    """ref fleet_base.py amp_init: pure-fp16 master-weight init. The XLA
    path keeps master weights implicitly (params stay f32; casts are
    inserted by the AMP transform), so this is a documented no-op."""
    return None


def save_persistables(executor, dirname, main_program=None, mode=0):
    """ref fleet_base.py save_persistables -> static Program.save."""
    import os
    from ...static import default_main_program
    prog = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    prog.save(os.path.join(dirname, "persistables"))


def save_inference_model(executor, dirname, feeded_var_names, target_vars,
                         main_program=None, export_for_deployment=True,
                         mode=0):
    """ref fleet_base.py save_inference_model -> static.io."""
    import os
    from ...static import default_main_program
    from ...static.io import save_inference_model as _sim
    prog = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    # the facade takes feed NAMES; resolve them to the program's feed vars
    feeds = [prog.feeds[n] for n in feeded_var_names]
    _sim(os.path.join(dirname, "model"), feeds, target_vars,
         executor, program=prog)


# the fleet OBJECT mirrors the reference singleton: every facade function
# must be reachable as fleet.<name> too
for _fn in (init_server, run_server, init_worker, stop_worker, shrink,
            minimize, step, clear_grad, get_lr, set_lr, state_dict,
            set_state_dict, amp_init, save_persistables,
            save_inference_model):
    setattr(_FleetModule, _fn.__name__, staticmethod(_fn))
del _fn
