"""Graph-learning PS service (ref paddle/fluid/distributed/service/
graph_py_service.h + graph_brpc_server.h + table/common_graph_table.h).

TPU-native redesign: the reference serves adjacency + sampling over brpc
to GPU workers; here the graph lives in the same C++ parameter server
(native/src/ps_server.cc GraphTable — sharded adjacency, uniform neighbor
sampling with -1 padding) over the length-prefixed-TCP protocol, and the
python side shapes every sample as a STATIC [n, k] block so the consuming
GNN step compiles once per fanout signature. Node features live in an
ordinary sparse table (pull_sparse by sampled id) — the same split the
reference makes between the graph table and feature storage.
"""
import numpy as np


class GraphService:
    """Client-side facade: build + multi-hop sample (GraphSAGE-style)."""

    def __init__(self, client, table_id=100, feature_table=None,
                 symmetric=True):
        self.client = client
        self.table_id = table_id
        self.feature_table = feature_table
        self.symmetric = symmetric

    # ------------------------------------------------------------- build
    def add_edges(self, src, dst):
        """Insert edges (both directions when symmetric — the reference
        loads reverse edges as a separate edge type). One concatenated RPC
        either way."""
        src = np.asarray(src, np.int64).ravel()
        dst = np.asarray(dst, np.int64).ravel()
        if self.symmetric:
            src, dst = (np.concatenate([src, dst]),
                        np.concatenate([dst, src]))
        self.client.add_edges(self.table_id, src, dst)

    def load_edge_file(self, path, delimiter="\t"):
        """ref graph_py_service load_edge_file: one 'src<TAB>dst' per line."""
        src, dst = [], []
        with open(path) as f:
            for line in f:
                parts = line.strip().split(delimiter)
                if len(parts) >= 2:
                    src.append(int(parts[0]))
                    dst.append(int(parts[1]))
        if src:
            self.add_edges(np.asarray(src), np.asarray(dst))
        return len(src)

    # ------------------------------------------------------------ queries
    def sample_neighbors(self, ids, k):
        return self.client.sample_neighbors(self.table_id, ids, k)

    def node_degree(self, ids):
        return self.client.node_degree(self.table_id, ids)

    def random_nodes(self, n):
        return self.client.random_nodes(self.table_id, n)

    def sample_subgraph(self, seed_ids, fanouts):
        """Multi-hop GraphSAGE frontier expansion: returns one [n_i, k_i]
        int64 block per hop (plus the seeds), each a static-shape gather
        index into the feature table — the TPU-friendly flattening of the
        reference's recursive sample_neighboors calls."""
        seeds = np.asarray(seed_ids, np.int64).ravel()
        hops = [seeds]
        frontier = seeds
        for k in fanouts:
            nb = self.sample_neighbors(frontier, k)       # [n, k]
            hops.append(nb)
            frontier = nb.ravel()
        return hops

    def pull_features(self, ids, dim):
        """Feature rows for (possibly -1-padded) ids; pads get zeros."""
        if self.feature_table is None:
            raise ValueError("GraphService built without a feature_table")
        flat = np.asarray(ids, np.int64).ravel()
        valid = flat >= 0
        rows = np.zeros((flat.size, dim), np.float32)
        if valid.any():
            # pull only the real ids: PULL_SPARSE lazily materialises rows
            # server-side, so pulling a pad-substitute id would create a
            # phantom feature row
            rows[valid] = np.asarray(self.client.pull_sparse(
                self.feature_table, flat[valid], dim), np.float32)
        return rows.reshape(tuple(np.asarray(ids).shape) + (dim,))
