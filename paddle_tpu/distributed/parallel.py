"""DataParallel (ref python/paddle/fluid/dygraph/parallel.py:322 + the bucketed
Reducer imperative/reducer.cc).

TPU-native rationale: the reference overlaps backward with bucketed NCCL
allreduce because grads materialise op-by-op on separate processes. Under
GSPMD there is one program: the train step is compiled over a Mesh with the
batch sharded on the 'dp' axis, and XLA inserts (and schedules/overlaps) the
gradient AllReduces itself — the Reducer's bucketing/overlap machinery is the
compiler's latency-hiding scheduler now. DataParallel therefore:
  * marks the model as data-parallel (TrainStep/hapi shard inputs on 'dp'),
  * keeps scale_loss/apply_collective_grads API compat as no-ops,
  * still works in eager mode (single-device semantics).
"""
import jax

from ..nn.layer import Layer
from . import mesh as mesh_mod


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        mesh_mod.default_mesh()
        self._data_parallel = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """ref parallel.py:506 — grads are psum-averaged by the compiled step;
        no pre-scaling needed."""
        return loss

    def apply_collective_grads(self):
        """ref parallel.py:515 — XLA inserts gradient AllReduce; no-op."""
        pass

    # delegate module surface to the wrapped layer
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    set_dict = set_state_dict
