"""DataParallel (ref python/paddle/fluid/dygraph/parallel.py:322 + the bucketed
Reducer imperative/reducer.cc).

TPU-native rationale: the reference overlaps backward with bucketed NCCL
allreduce because grads materialise op-by-op on separate processes. Under
GSPMD there is one program: the train step is compiled over a Mesh with the
batch sharded on the 'dp' axis, and XLA inserts (and schedules/overlaps) the
gradient AllReduces itself — the Reducer's bucketing/overlap machinery is the
compiler's latency-hiding scheduler now. DataParallel therefore:
  * marks the model as data-parallel (TrainStep/hapi shard inputs on 'dp'),
  * keeps scale_loss/apply_collective_grads API compat as no-ops,
  * still works in eager mode (single-device semantics).
"""
import jax

from ..nn.layer import Layer
from . import mesh as mesh_mod


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        mesh_mod.default_mesh()
        self._data_parallel = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """ref parallel.py:506 — the eager multi-process path averages in
        apply_collective_grads; the compiled GSPMD path psum-averages in the
        partitioned program. Either way no pre-scaling here."""
        return loss

    def apply_collective_grads(self):
        """ref parallel.py:515 + imperative/reducer.cc. Under one compiled
        step, XLA inserts the gradient AllReduce (no-op here). In EAGER
        multi-process mode (jax.distributed initialised by
        init_parallel_env / the launcher), this is a real cross-process
        gradient mean over the coordination service — the dygraph Reducer's
        allreduce, batched into one fused collective per call.

        COMPAT SHIM ONLY, not a perf path: the eager mean stages through
        host numpy (process_allgather -> np mean -> re-upload) per call,
        a device->host->device round-trip the reference does as bucketed
        in-place NCCL. The compiled GSPMD path (ShardedTrainStep /
        strategy transforms) is the performance-bearing DP implementation;
        keep eager DP out of any benchmark or perf claim."""
        try:
            nproc = jax.process_count()
        except (RuntimeError, ValueError):
            nproc = 1
        if nproc <= 1:
            return
        from jax.experimental import multihost_utils
        import jax.numpy as jnp
        from ..framework.selected_rows import SelectedRows
        from ..framework.tensor import Tensor as _T
        params = [p for _, p in self._layers.named_parameters()
                  if p.grad is not None and not p.stop_gradient]
        if not params:
            return
        for p in params:
            if isinstance(p.grad, SelectedRows):
                # cross-process mean needs aligned dense buffers
                p.grad = _T(p.grad.to_dense())
        # one fused collective for the whole bucket (reducer.cc's bucketed
        # allreduce): gather each grad across processes, mean over them
        import numpy as np
        grads = [p.grad._data for p in params]
        gathered = multihost_utils.process_allgather(tuple(grads))
        for p, g in zip(params, gathered):
            # back to a plain local array: the gather result is a global
            # (process-spanning) Array that local eager ops can't consume
            local = np.asarray(jax.device_get(g)).mean(axis=0)
            p.grad._data = jnp.asarray(local)

    # delegate module surface to the wrapped layer
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    set_dict = set_state_dict
