"""1F1B pipeline schedule over a 'pp' device axis
(ref python/paddle/fluid/optimizer.py:3718 PipelineOptimizer +
paddle/fluid/framework/section_worker.cc RunFThenB/Run1F1B micro loops).

TPU-native redesign — the reference runs per-stage C++ worker threads with
send/recv ops; here the whole schedule is ONE jitted program:

  - The (S stages, M microbatches) 1F1B timetable is *simulated on the host*
    at trace time into static action tables DO_F/F_M/DO_B/B_M [T, S] —
    deterministic given (S, M), so the device program carries no scheduling
    state. Stage r reads its column via lax.axis_index inside shard_map.
  - Each tick: activations ppermute one hop forward, cotangents one hop
    back (explicit ICI neighbor traffic, the send_v2/recv_v2 analog), then
    every device lax.cond-executes its scheduled action — TPUs execute
    per-core control flow, so fwd/bwd/idle diverge freely across stages.
  - Backward is hand-rolled: a stage saves only its INPUT activation per
    in-flight microbatch (ring buffer of S slots — the 1F1B memory bound:
    ≤ S live activations per stage vs GPipe's M) and recomputes the stage
    under jax.vjp at backward time (remat-style, like the reference's
    recompute+pipeline composition).
  - The last stage fuses stage-forward + head + loss into one vjp closure,
    so its F tick only banks the input; loss and d(loss) emerge on its B
    tick — the classic 1F1B "loss immediately follows arrival" behavior.

Composability (ref fleet/meta_optimizers/pipeline_optimizer.py:232, which
inserts per-ring allreduce to compose pipeline with DP): the schedule is
MANUAL only over 'pp' (jax.shard_map axis_names={'pp'}); any other mesh
axes (dp, mp) stay AUTO, so GSPMD shards the per-stage compute over them
and inserts the dp gradient psums and Megatron mp collectives itself —
the Megatron dp×mp×pp production shape with 1F1B memory behavior, without
hand-written per-ring allreduces. Peak-memory, not bubble, is what 1F1B
buys: both schedules idle (S-1)-ish slots per wave, but 1F1B retires
microbatch m's activations after its backward instead of after ALL
forwards.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import state
from . import mesh as mesh_mod


def simulate_1f1b(S, M):
    # ptlint baseline: host-sync-in-trace findings here are
    # grandfathered — S/M are python ints, this is trace-time static
    # schedule precomputation (pure host numpy, no tracers enter it)
    """Host-side schedule simulation (the depth-first 1F1B rule: a stage runs
    a backward whenever one is ready, else a forward, with in-flight capped
    at S - r — ref section_worker.cc Run1F1B / Megatron's non-interleaved
    schedule). One compute slot per (tick, stage).

    Returns action tables of shape [T, S]:
      DO_F/F_M     stage computes forward of microbatch F_M
      DO_B/B_M     stage computes backward of microbatch B_M
      RECV_F/F_RM  an activation for microbatch F_RM arrives from upstream
                   (sent on the previous tick) and must be banked
      RECV_B/B_RM  a cotangent arrives from downstream and must be banked
    plus stats (T, max in-flight per stage, bubble_fraction) for tests."""
    fwd_done = [0] * S          # forwards computed per stage
    bwd_done = [0] * S
    arr_f = [0] * S             # activations banked (arr_f[0] ~ injection)
    arr_b = [0] * S             # cotangents banked
    DO_F, F_M, DO_B, B_M = [], [], [], []
    RECV_F, F_RM, RECV_B, B_RM = [], [], [], []
    max_inflight = [0] * S
    t = 0
    while min(bwd_done) < M and t < 8 * (M + 2 * S) + 16:
        # arrivals: what neighbors computed on the previous tick lands now
        recv_f = [False] * S
        f_rm = [0] * S
        recv_b = [False] * S
        b_rm = [0] * S
        if t > 0:
            for r in range(1, S):
                if DO_F[-1][r - 1]:
                    recv_f[r] = True
                    f_rm[r] = F_M[-1][r - 1]
                    arr_f[r] += 1
            for r in range(S - 1):
                if DO_B[-1][r + 1]:
                    recv_b[r] = True
                    b_rm[r] = B_M[-1][r + 1]
                    arr_b[r] += 1
        do_f = [False] * S
        f_m = [0] * S
        do_b = [False] * S
        b_m = [0] * S
        for r in range(S):
            mf, mb_ = fwd_done[r], bwd_done[r]
            can_f = mf < M and (mf < arr_f[r] if r else True) \
                and (mf - mb_) < (S - r)          # 1F1B in-flight cap
            can_b = mb_ < M and (mb_ < arr_b[r] if r < S - 1
                                 else mb_ < fwd_done[r])
            if can_b:                             # depth-first: drain bwds
                do_b[r] = True
                b_m[r] = mb_
                bwd_done[r] = mb_ + 1
            elif can_f:
                do_f[r] = True
                f_m[r] = mf
                fwd_done[r] = mf + 1
        DO_F.append(do_f)
        F_M.append(f_m)
        DO_B.append(do_b)
        B_M.append(b_m)
        RECV_F.append(recv_f)
        F_RM.append(f_rm)
        RECV_B.append(recv_b)
        B_RM.append(b_rm)
        for r in range(S):
            max_inflight[r] = max(max_inflight[r],
                                  fwd_done[r] - bwd_done[r])
        t += 1
    assert min(bwd_done) >= M, "1F1B schedule did not converge"
    busy = int(np.sum(DO_F) + np.sum(DO_B))
    return {
        "DO_F": np.asarray(DO_F), "F_M": np.asarray(F_M, np.int32),
        "DO_B": np.asarray(DO_B), "B_M": np.asarray(B_M, np.int32),
        "RECV_F": np.asarray(RECV_F), "F_RM": np.asarray(F_RM, np.int32),
        "RECV_B": np.asarray(RECV_B), "B_RM": np.asarray(B_RM, np.int32),
        "T": t, "max_inflight": max_inflight,
        "bubble_fraction": 1.0 - busy / float(t * S),
    }


def pipeline_1f1b(stage_fn, last_loss_fn, blocks_p, post_p, x_micro,
                  labels_micro, mesh=None, pp_axis=None):
    """Run 1F1B over the 'pp' mesh axis.

    stage_fn(stage_params, x) -> y            per-stage forward chunk
    last_loss_fn(stage_params, post_params, x, labels) -> scalar microloss
        (last stage chunk + head + loss fused; vjp'd at backward time)
    blocks_p: dict of [S, ...] arrays (stage-stacked, sharded over pp)
    post_p:   dict of unstacked head/norm params (replicated)
    x_micro:  [M, mb, ...] first-stage inputs;  labels_micro: [M, ...]

    Returns (mean_loss, grads_stacked [S, ...], post_grads, dx_micro) —
    dx_micro feeds the embedding backward outside the engine.
    """
    mesh = mesh or mesh_mod.get_mesh()
    axis = pp_axis or mesh_mod.PP_AXIS
    S = int(mesh.shape[axis])
    M = int(x_micro.shape[0])
    sched = simulate_1f1b(S, M)
    tables = tuple(jnp.asarray(sched[k]) for k in
                   ("DO_F", "F_M", "DO_B", "B_M",
                    "RECV_F", "F_RM", "RECV_B", "B_RM"))

    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]
    mb_shape = x_micro.shape[1:]
    lab_shape = labels_micro.shape[1:]

    def body(blocks_local, post_local, xm, labm):
        # blocks_local: [1, ...] local stage slice -> squeeze
        params = jax.tree.map(lambda a: a[0], blocks_local)
        me = lax.axis_index(axis)
        # vma discipline (check_vma=True on hybrid meshes): every stage
        # computes different values, so mark ALL inputs varying over 'pp'
        # up front — cond branches then agree on types
        def _v(a):
            # idempotent: stacked inputs (P over pp) arrive already varying.
            # lax.pcast is the current invariant->varying cast; pvary is its
            # deprecated alias (kept as fallback for older jax).
            vma = getattr(jax.typeof(a), "vma", frozenset())
            if axis in vma:
                return a
            if hasattr(lax, "pcast"):
                return lax.pcast(a, (axis,), to="varying")
            return lax.pvary(a, (axis,))

        vary = lambda t: jax.tree.map(_v, t)
        params = vary(params)
        post_local = vary(post_local)
        xm = vary(xm)
        labm = vary(labm)

        def fwd_of(x):
            return stage_fn(params, x)

        def loss_vjp(x, lab):
            def f(p, pp_, xx):
                return last_loss_fn(p, pp_, xx, lab)
            loss, pull = jax.vjp(f, params, post_local, x)
            dp, dpost, dx = pull(_v(jnp.asarray(1.0 / M, loss.dtype)))
            return loss, dp, dpost, dx

        def tick(carry, xs):
            (fwd_send, bwd_send, save, cot, gacc, gpost, loss_acc,
             dx_acc) = carry
            (do_f_row, f_m_row, do_b_row, b_m_row,
             recv_f_row, f_rm_row, recv_b_row, b_rm_row) = xs
            recv_act = lax.ppermute(fwd_send, axis, fwd_perm)
            recv_cot = lax.ppermute(bwd_send, axis, bwd_perm)

            # ---------------- bank arrivals (latch: a value may wait several
            # ticks between send and consumption)
            def bank_f(save):
                m = f_rm_row[me]
                return lax.dynamic_update_index_in_dim(
                    save, recv_act.astype(save.dtype), m % S, 0)

            save = lax.cond(recv_f_row[me], bank_f, lambda s: s, save)

            def bank_b(cot):
                m = b_rm_row[me]
                return lax.dynamic_update_index_in_dim(
                    cot, recv_cot.astype(cot.dtype), m % S, 0)

            cot = lax.cond(recv_b_row[me], bank_b, lambda c: c, cot)

            do_f = do_f_row[me]
            do_b = do_b_row[me]
            mf = f_m_row[me]
            mb_i = b_m_row[me]

            # ---------------- forward action
            def run_f(op):
                fwd_send, save = op
                # stage 0 injects from the stream; others read the bank
                x_in = jnp.where(me == 0, xm[mf], save[mf % S])
                save = lax.dynamic_update_index_in_dim(save, x_in, mf % S, 0)
                # last stage: bank only; its compute is fused with the loss
                # vjp on its backward tick
                y = jnp.where(me == S - 1, fwd_send,
                              fwd_of(x_in).astype(fwd_send.dtype))
                return y, save

            fwd_send, save = lax.cond(do_f, run_f, lambda op: op,
                                      (fwd_send, save))

            # ---------------- backward action
            def run_b(op):
                bwd_send, gacc, gpost, loss_acc, dx_acc = op
                x_sv = save[mb_i % S]

                def last_branch(_):
                    loss, dp, dpost, dx = loss_vjp(x_sv, labm[mb_i])
                    return loss, dp, dpost, dx

                def mid_branch(_):
                    def f(p, xx):
                        return stage_fn(p, xx)
                    _, pull = jax.vjp(f, params, x_sv)
                    dp, dx = pull(cot[mb_i % S].astype(x_sv.dtype))
                    zero_post = jax.tree.map(jnp.zeros_like, post_local)
                    return (_v(jnp.asarray(0.0, jnp.float32)), dp, zero_post,
                            dx)

                loss_m, dp, dpost, dx = lax.cond(me == S - 1, last_branch,
                                                 mid_branch, None)
                gacc = jax.tree.map(jnp.add, gacc, dp)
                gpost = jax.tree.map(jnp.add, gpost, dpost)
                loss_acc = loss_acc + loss_m.astype(jnp.float32)
                dx_acc = lax.cond(
                    me == 0,
                    lambda d: lax.dynamic_update_index_in_dim(
                        d, dx.astype(d.dtype), mb_i, 0),
                    lambda d: d, dx_acc)
                return dx.astype(bwd_send.dtype), gacc, gpost, loss_acc, dx_acc

            bwd_send, gacc, gpost, loss_acc, dx_acc = lax.cond(
                do_b, run_b, lambda op: op,
                (bwd_send, gacc, gpost, loss_acc, dx_acc))

            return (fwd_send, bwd_send, save, cot, gacc, gpost, loss_acc,
                    dx_acc), None

        zeros_act = jnp.zeros(mb_shape, x_micro.dtype)
        carry0 = vary((
            zeros_act,                                   # fwd_send
            zeros_act,                                   # bwd_send (cot)
            jnp.zeros((S,) + mb_shape, x_micro.dtype),   # input bank ring
            jnp.zeros((S,) + mb_shape, x_micro.dtype),   # cotangent ring
            jax.tree.map(jnp.zeros_like, params),        # gacc
            jax.tree.map(jnp.zeros_like, post_local),    # gpost
            jnp.zeros((), jnp.float32),                  # loss_acc
            jnp.zeros((M,) + mb_shape, x_micro.dtype),   # dx per micro
        ))
        carry, _ = lax.scan(tick, carry0, tables)
        _, _, _, _, gacc, gpost, loss_acc, dx_acc = carry
        loss = lax.psum(loss_acc, axis) / M              # only last stage != 0
        gpost = lax.psum(gpost, axis)                    # only last stage != 0
        dx = lax.psum(dx_acc, axis)                      # only stage 0 != 0
        gacc = jax.tree.map(lambda a: a[None], gacc)     # restack [1, ...]
        return loss, gacc, gpost, dx

    stacked = P(axis)
    rep = P()
    # manual ONLY over the pp axis: other mesh axes (dp/mp) remain auto, so
    # GSPMD shards the per-stage math over them (Megatron mp matmuls, dp
    # batch) and inserts their collectives — the hybrid composition path
    hybrid = len(mesh.axis_names) > 1
    f = jax.shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: stacked, blocks_p), rep, rep, rep),
        out_specs=(rep, jax.tree.map(lambda _: stacked, blocks_p), rep, rep),
        axis_names=frozenset({axis}),
        # partial-manual requires the vma machinery (jax's check_vma=False
        # path assumes full-manual in _unmatch); pure-pp keeps the cheaper
        # unchecked mode
        check_vma=hybrid)
    return f(blocks_p, post_p, x_micro, labels_micro)


# --------------------------------------------------------------------------
# full train step
# --------------------------------------------------------------------------

class OneF1BTrainStep:
    """Compiled 1F1B training step over a mesh with a 'pp' axis — pure-pp or
    hybrid dp×mp×pp (the memory-lean alternative to
    pipeline.PipelineTrainStep's GPipe-as-scan; ref section_worker.cc
    Run1F1B + pipeline_optimizer.py:232 pipeline×DP composition). The
    schedule is manual over 'pp' only; dp/mp axes are GSPMD-auto, with
    Megatron mp specs taken from the parameters' sharding hints. Accepts
    any model decomposable via pipeline.PipelineParts — not just GPT.

    Dropout inside pipelined blocks is not key-threaded here (the engine's
    stage replay is deterministic); train with dropout=0 in the trunk or use
    the GPipe engine, which threads per-(tick, stage, layer) keys.
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None, num_micro=8,
                 num_stages=None, donate=True, parts=None):
        from .pipeline import (PipelineParts, resolve_parts, _stacked_spec,
                               stack_block_params, unstack_block_params)
        from ..framework.tensor import Tensor as _T
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh or mesh_mod.get_mesh()
        axis = mesh_mod.PP_AXIS
        assert self.mesh is not None and axis in self.mesh.axis_names, \
            "1F1B needs a mesh with a 'pp' axis"
        S = num_stages or int(self.mesh.shape[axis])
        self.num_stages, self.num_micro = S, num_micro
        self.parts = parts or resolve_parts(model, loss_fn)
        blocks = self.parts.blocks
        L = len(blocks)
        assert L % S == 0, f"{L} layers not divisible by {S} stages"
        self.lps = L // S
        self.blocks_layer = blocks[0]

        stacked = {n: a.reshape((S, self.lps) + a.shape[1:])
                   for n, a in stack_block_params(blocks).items()}
        pre_p = {n: p._data for n, p in self.parts.pre.named_parameters()}
        post_p = ({n: p._data for n, p in self.parts.post.named_parameters()}
                  if self.parts.post is not None else {})

        self.params = {}
        self.params.update({"pre." + n: a for n, a in pre_p.items()})
        self.params.update({"blocks." + n: a for n, a in stacked.items()})
        self.params.update({"post." + n: a for n, a in post_p.items()})
        opt_state = optimizer.init_opt_state(self.params)
        self.opt_state = opt_state
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()

        pre_layer = self.parts.pre
        blocks_layer = self.blocks_layer
        head_call = self.parts.head_call
        post_layer = self.parts.post
        loss_fn_ = loss_fn
        mesh_ = self.mesh
        M = num_micro

        def stage_fn(stage_params, x):
            # stage_params: [lps, ...] -> scan the layer chunk
            def layer_body(h, lp):
                out, _ = blocks_layer.functional_call(lp, {}, _T(h))
                return (out._data if isinstance(out, _T) else out), None
            y, _ = lax.scan(layer_body, x, stage_params)
            return y

        def last_loss_fn(stage_params, bundle, x, labels):
            h = stage_fn(stage_params, x)
            post_b = bundle["post"]
            pre_b = bundle["pre"]
            if head_call is not None:
                return head_call(post_b, pre_b, h, labels)
            if post_layer is not None:
                out, _ = post_layer.functional_call(post_b, {}, _T(h))
                h = out._data if isinstance(out, _T) else out
            l = loss_fn_(_T(h), _T(labels))
            return l._data if isinstance(l, _T) else l

        def _step(params, opt_state, key, lr, step_i, ids_micro,
                  labels_micro):
            pre = {n[4:]: a for n, a in params.items()
                   if n.startswith("pre.")}
            blocks_p = {n[7:]: a for n, a in params.items()
                        if n.startswith("blocks.")}
            post = {n[5:]: a for n, a in params.items()
                    if n.startswith("post.")}

            def embed(pre_p):
                def one(i, k):
                    with state.functional_rng_ctx(k):
                        out, _ = pre_layer.functional_call(pre_p, {}, _T(i))
                    return out._data if isinstance(out, _T) else out
                return jax.vmap(one)(ids_micro, jax.random.split(key, M))

            x_micro, pre_pull = jax.vjp(embed, pre)
            bundle = {"post": post, "pre": pre}
            loss, gblocks, gbundle, dx = pipeline_1f1b(
                stage_fn, last_loss_fn, blocks_p, bundle, x_micro,
                labels_micro, mesh=mesh_)
            (dpre_embed,) = pre_pull(dx)
            grads = {}
            grads.update({"pre." + n: dpre_embed[n] + gbundle["pre"][n]
                          for n in pre})
            grads.update({"blocks." + n: a for n, a in gblocks.items()})
            grads.update({"post." + n: a for n, a in gbundle["post"].items()})
            new_params, new_opt = apply_fn(params, grads, opt_state, lr,
                                           step_i)
            return loss, new_params, new_opt

        from jax.sharding import NamedSharding
        rep = NamedSharding(self.mesh, P())
        # Megatron mp hints from the parameters, composed with the pp stage
        # dim for the stacked trunk (same spec helpers as the GPipe engine).
        # pre/post (embedding + head) stay REPLICATED: a vocab-parallel
        # embedding entering the partial-manual pp region trips an XLA SPMD
        # partitioner CHECK (spmd_partitioner_util.cc:495); the trunk is
        # where the Megatron specs matter.
        hints = {n: getattr(p, "sharding", None)
                 for n, p in self.blocks_layer.named_parameters()}
        param_sh = {}
        for n, a in self.params.items():
            if n.startswith("blocks."):
                spec = _stacked_spec(hints.get(n[len("blocks."):]),
                                     self.mesh, a.shape, mesh_mod.PP_AXIS)
                param_sh[n] = NamedSharding(self.mesh, spec)
            else:
                param_sh[n] = rep
        opt_sh = {n: {sn: param_sh[n] for sn in slots}
                  for n, slots in self.opt_state.items()}
        self.params = {n: jax.device_put(a, param_sh[n])
                       for n, a in self.params.items()}
        self.opt_state = {n: {sn: jax.device_put(a, param_sh[n])
                              for sn, a in slots.items()}
                          for n, slots in self.opt_state.items()}
        # microbatched data [M, mb, ...]: shard the within-microbatch batch
        # dim over dp when the mesh has one (GSPMD splits each stage's math)
        dp = (mesh_mod.DP_AXIS
              if mesh_mod.DP_AXIS in self.mesh.axis_names else None)
        data_sh = NamedSharding(self.mesh, P(None, dp)) if dp else rep
        self._compiled = jax.jit(
            _step,
            in_shardings=(param_sh, opt_sh, None, None, None, data_sh,
                          data_sh),
            out_shardings=(rep, param_sh, opt_sh),
            donate_argnums=(0, 1) if donate else ())
        self._unstack = unstack_block_params

    def _microbatch(self, a):
        from ..framework.tensor import Tensor as _T
        a = a._data if isinstance(a, _T) else jnp.asarray(a)
        b = a.shape[0]
        M = self.num_micro
        assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
        return a.reshape((M, b // M) + a.shape[1:])

    def __call__(self, inputs, labels):
        from ..framework import state as _state
        from ..framework.tensor import Tensor as _T
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        with self.mesh:
            loss, self.params, self.opt_state = self._compiled(
                self.params, self.opt_state, _state.next_rng_key(), lr,
                jnp.asarray(self._step_i, jnp.int32),
                self._microbatch(inputs), self._microbatch(labels))
        return _T(loss)

    def sync(self):
        S, lps = self.num_stages, self.lps
        named = {}
        named.update({"pre." + n: p
                      for n, p in self.parts.pre.named_parameters()})
        if self.parts.post is not None:
            named.update({"post." + n: p
                          for n, p in self.parts.post.named_parameters()})
        stacked = {}
        for n, arr in self.params.items():
            if n.startswith("blocks."):
                a = jax.device_get(arr)
                stacked[n[len("blocks."):]] = a.reshape((S * lps,)
                                                        + a.shape[2:])
            else:
                named[n]._data = jnp.copy(jax.device_get(arr))
        self._unstack(self.parts.blocks, stacked)
        self.optimizer._global_step = self._step_i
