"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

NEW capability relative to the reference (SURVEY.md §5: Yelrose/Paddle has no
sequence parallelism; its only long-sequence coping mechanisms are recompute +
pipeline). TPU-native design (PAPERS.md Ring Attention, arXiv:2310.01889):

  - Q, K, V are sharded along the sequence dim over the 'sp' axis.
  - Each device keeps its Q shard resident and streams K/V shards around the
    ICI ring with `lax.ppermute` inside `shard_map`; partial softmax outputs
    are merged with (out, logsumexp) online-softmax statistics, so no device
    ever materialises more than an (S/n x S/n) score block.
  - The K/V rotation is expressed as a `lax.scan`, so XLA's latency-hiding
    scheduler overlaps each ppermute with the next block's compute.
  - Backward is a hand-rolled SECOND ring pass (custom_vjp): dk/dv
    accumulators travel with their k/v shards around the ring and arrive
    home after n hops; block probabilities are recomputed from the saved
    global logsumexp, so residuals are strictly local O(S/n) — the scan's
    per-step k/v carries are never saved.

Communication rides the 'sp' ring only; composes freely with 'dp' (batch),
'mp' (heads/hidden via GSPMD outside the shard_map), and 'pp'.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod


def _masked_scores(q, k, scale, causal, q_off, k_off):
    """Scaled (+causally masked) scores — the ONE definition both the
    forward ring and the hand-rolled backward recompute from, so the
    gradient's probabilities can never drift from the forward's."""
    sq, sk = q.shape[-2], k.shape[-2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = q_off + jnp.arange(sq)[:, None]
        ki = k_off + jnp.arange(sk)[None, :]
        s = jnp.where(ki <= qi, s, -jnp.inf)
    return s


def _block_attn(q, k, v, scale, causal, q_off, k_off):
    """One attention block. q:[B,H,Sq,D], k/v:[B,H,Sk,D] ->
    (normalised block output [B,H,Sq,D], logsumexp [B,H,Sq]).

    q_off/k_off are the global sequence offsets of the shards (k_off is
    traced — it depends on the ring step)."""
    s = _masked_scores(q, k, scale, causal, q_off, k_off)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)   # fully-masked rows
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    o = o / jnp.maximum(l, 1e-30)
    lse = jnp.where(l[..., 0] > 0,
                    m_safe[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)),
                    -jnp.inf)
    return o, lse


def _merge(o1, lse1, o2, lse2):
    """Combine two partial softmax results by their logsumexp statistics."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(lse1 - lse), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(lse2 - lse), 0.0)
    return o1 * w1[..., None] + o2 * w2[..., None], lse


_KV_CHUNK = 512        # flash-style tile inside a ring block: working
                       # set is chunk x S_loc instead of S_loc^2


def _split_kv_chunks(k, v):
    """Shared tiling split for forward AND backward: full _KV_CHUNK tiles
    scan-major ([nch, ..., chunk, D]) plus an optional remainder tail, so
    the linear-memory guarantee holds for EVERY shard length.
    Returns (kc, vc, offs, k_tail, v_tail, tail_off) — kc is None when
    the shard fits in one tile."""
    sk = k.shape[-2]
    nch, rem = divmod(sk, _KV_CHUNK)
    if nch == 0 or (nch == 1 and rem == 0):
        return None, None, None, k, v, 0
    head = nch * _KV_CHUNK
    kc = jnp.moveaxis(
        k[..., :head, :].reshape(k.shape[:-2] + (nch, _KV_CHUNK,
                                                 k.shape[-1])), -3, 0)
    vc = jnp.moveaxis(
        v[..., :head, :].reshape(v.shape[:-2] + (nch, _KV_CHUNK,
                                                 v.shape[-1])), -3, 0)
    offs = jnp.arange(nch) * _KV_CHUNK
    k_tail = k[..., head:, :] if rem else None
    v_tail = v[..., head:, :] if rem else None
    return kc, vc, offs, k_tail, v_tail, head


def _block_attn_tiled(q, k, v, scale, causal, q_off, k_off):
    """_block_attn with the k/v axis tiled by _KV_CHUNK (online-softmax
    merge per tile) so the score working set stays O(S_loc * chunk)."""
    kc, vc, offs, k_tail, v_tail, tail_off = _split_kv_chunks(k, v)
    if kc is None:
        return _block_attn(q, k_tail, v_tail, scale, causal, q_off, k_off)

    def body(carry, inp):
        o, lse = carry
        k_t, v_t, off = inp
        o_b, lse_b = _block_attn(q, k_t, v_t, scale, causal, q_off,
                                 k_off + off)
        return _merge(o, lse, o_b, lse_b), None

    o0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    lse0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)
    (o, lse), _ = lax.scan(body, (o0, lse0), (kc, vc, offs))
    if k_tail is not None:
        o_b, lse_b = _block_attn(q, k_tail, v_tail, scale, causal, q_off,
                                 k_off + tail_off)
        o, lse = _merge(o, lse, o_b, lse_b)
    return o, lse


def _ring_forward(q, k, v, axis_name, causal, scale):
    """Forward ring pass. Returns (o [B,H,S/n,D] f32, lse [B,H,S/n])."""
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    s_loc = q.shape[-2]
    q_off = me * s_loc
    qf = q.astype(jnp.float32) if q.dtype != jnp.float32 else q
    perm = [(j, (j + 1) % n) for j in range(n)]

    block = jax.checkpoint(
        functools.partial(_block_attn_tiled, scale=scale, causal=causal,
                          q_off=q_off))

    def body(carry, t):
        k_cur, v_cur, o, lse = carry
        src = jnp.mod(me - t, n)                 # owner of the block we hold
        o_b, lse_b = block(qf, k_cur, v_cur, k_off=src * s_loc)
        o, lse = _merge(o, lse, o_b, lse_b)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o, lse), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)
    (k, v, o, lse), _ = lax.scan(body, (k, v, o0, lse0), jnp.arange(n))
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_shard_cvjp(q, k, v, axis_name, causal, scale):
    o, _ = _ring_forward(q, k, v, axis_name, causal, scale)
    return o.astype(q.dtype)


def _ring_cvjp_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_forward(q, k, v, axis_name, causal, scale)
    # residuals are strictly LOCAL O(S/n) — the rotation is re-run in
    # backward instead of saving every scan step's k/v carry (which would
    # be the full sequence per device)
    return o.astype(q.dtype), (q, k, v, o, lse)


def _ring_cvjp_bwd(axis_name, causal, scale, res, do):
    """Second ring pass (PAPERS.md Ring Attention backward): dk/dv
    accumulators travel WITH their k/v shards around the ring, arriving
    home after n hops; dq stays local. Block probabilities are recomputed
    from the saved global logsumexp — the flash-attention backward
    identity ds = p * (dp - rowsum(do*o))."""
    q, k, v, o, lse = res
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    s_loc = q.shape[-2]
    q_off = me * s_loc
    perm = [(j, (j + 1) % n) for j in range(n)]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    Dvec = jnp.sum(dof * o, axis=-1)                      # [B,H,Sq]
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
    live = jnp.isfinite(lse)[..., None]                   # masked-out rows

    def one_tile(k_c, v_c, k_off):
        s = _masked_scores(qf, k_c, scale, causal, q_off, k_off)
        p = jnp.where(live, jnp.exp(s - lse_safe[..., None]), 0.0)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_c.astype(jnp.float32))
        ds = p * (dp - Dvec[..., None])
        dq_b = jnp.einsum("bhqk,bhkd->bhqd", ds,
                          k_c.astype(jnp.float32)) * scale
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        return dq_b, dk_b, dv_b

    def one_block(k_c, v_c, k_off):
        """Tiled like the forward (same _split_kv_chunks): dk/dv come back
        chunk-stacked and are re-folded, dq accumulates across tiles."""
        kc, vc, offs, k_tail, v_tail, tail_off = _split_kv_chunks(k_c, v_c)
        if kc is None:
            return one_tile(k_tail, v_tail, k_off)

        def body(dq_acc, inp):
            k_t, v_t, off = inp
            dq_b, dk_t, dv_t = one_tile(k_t, v_t, k_off + off)
            return dq_acc + dq_b, (dk_t, dv_t)

        dq_b, (dks, dvs) = lax.scan(
            body, jnp.zeros(q.shape, jnp.float32), (kc, vc, offs))
        head = offs.shape[0] * _KV_CHUNK
        dk_b = jnp.moveaxis(dks, 0, -3).reshape(
            k_c.shape[:-2] + (head, k_c.shape[-1]))
        dv_b = jnp.moveaxis(dvs, 0, -3).reshape(
            v_c.shape[:-2] + (head, v_c.shape[-1]))
        if k_tail is not None:
            dq_t, dk_t, dv_t = one_tile(k_tail, v_tail, k_off + tail_off)
            dq_b = dq_b + dq_t
            dk_b = jnp.concatenate([dk_b, dk_t], axis=-2)
            dv_b = jnp.concatenate([dv_b, dv_t], axis=-2)
        return dq_b, dk_b, dv_b

    def body(carry, t):
        k_c, v_c, dk_c, dv_c, dq = carry
        src = jnp.mod(me - t, n)
        dq_b, dk_b, dv_b = one_block(k_c, v_c, src * s_loc)
        dq = dq + dq_b
        dk_c = dk_c + dk_b
        dv_c = dv_c + dv_b
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        dk_c = lax.ppermute(dk_c, axis_name, perm)
        dv_c = lax.ppermute(dv_c, axis_name, perm)
        return (k_c, v_c, dk_c, dv_c, dq), None

    zeros_kv = jnp.zeros(k.shape, jnp.float32)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    (_, _, dk, dv, dq), _ = lax.scan(
        body, (k, v, zeros_kv, zeros_kv, dq0), jnp.arange(n))
    # n rotations of +1 bring each shard (and its grad) back home
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_shard_cvjp.defvjp(_ring_cvjp_fwd, _ring_cvjp_bwd)


def ring_attention_shard(q, k, v, *, axis_name, causal, scale):
    """Per-shard body (call inside shard_map). q/k/v: local [B,H,S/n,D]."""
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _ring_shard_cvjp(q, k, v, axis_name, causal, sc)


@functools.lru_cache(maxsize=64)
def _jitted_ring(mesh, axis_name, causal, scale, batch_axis, head_axis):
    """One jitted shard_map per (mesh, config) — jax.jit caches on callable
    identity, so rebuilding the closure per call would recompile every
    attention layer every step."""
    spec = P(batch_axis, head_axis, axis_name, None)
    f = jax.shard_map(
        functools.partial(ring_attention_shard, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    # jit: custom_vjp calls inside shard_map are not eagerly evaluable
    return jax.jit(f)


def ring_attention(q, k, v, causal=False, scale=None,
                   axis_name=mesh_mod.SP_AXIS, mesh=None):
    """Array-level ring attention over globally-shaped [B,H,S,D] arrays.

    Shards the sequence dim over `axis_name` of the current mesh (and the
    batch dim over 'dp' when present). Falls back to single-device flash
    attention when the mesh has no (or a trivial) 'sp' axis."""
    mesh = mesh or mesh_mod.get_mesh()
    if (mesh is None or axis_name not in mesh.axis_names
            or int(mesh.shape[axis_name]) == 1):
        from ..ops.pallas.flash_attention import _flash_array
        return _flash_array(q, k, v, causal=causal, scale=scale)
    if q.shape[-2] % int(mesh.shape[axis_name]) != 0:
        raise ValueError(
            f"sequence length {q.shape[-2]} not divisible by sp="
            f"{mesh.shape[axis_name]}")
    batch_axis = mesh_mod.DP_AXIS if (
        mesh_mod.DP_AXIS in mesh.axis_names
        and q.shape[0] % int(mesh.shape[mesh_mod.DP_AXIS]) == 0) else None
    # heads ride 'mp' (Megatron head-sharded QKV stays sharded through the
    # ring — nothing in the shard body mixes heads)
    head_axis = mesh_mod.MP_AXIS if (
        mesh_mod.MP_AXIS in mesh.axis_names
        and q.shape[1] % int(mesh.shape[mesh_mod.MP_AXIS]) == 0) else None
    # scale is a nondiff static of the custom_vjp: it must be a python
    # float (a traced scale would leak into the bwd rule)
    scale_f = None if scale is None else float(scale)
    f = _jitted_ring(mesh, axis_name, bool(causal), scale_f, batch_axis,
                     head_axis)
    return f(q, k, v)


def ring_flash_attention(q, k, v, causal=False, scale=None,
                         axis_name=mesh_mod.SP_AXIS, mesh=None):
    """Tensor-level op (tape/functional integrated via the dispatcher)."""
    from ..ops.dispatch import apply

    def fn(q_, k_, v_):
        return ring_attention(q_, k_, v_, causal=causal, scale=scale,
                              axis_name=axis_name, mesh=mesh)

    return apply(fn, (q, k, v), name="ring_flash_attention")
