"""hapi callbacks (ref python/paddle/hapi/callbacks.py: Callback, ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL)."""
import numbers
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin",
                lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end",
                lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def on_begin(self, mode, logs=None):
        self.set_params(logs)
        for cb in self.callbacks:
            cb.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for cb in self.callbacks:
            cb.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for cb in self.callbacks:
            cb.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for cb in self.callbacks:
            cb.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
            print(f"step {step}: " + ", ".join(items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = [f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                     if isinstance(v, numbers.Number)]
            dt = time.time() - getattr(self, "_t0", time.time())
            print(f"Epoch {epoch}: " + ", ".join(items) + f" ({dt:.1f}s)")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:                     # explicit: 0.0 is a real value
            cur = logs.get(f"eval_{self.monitor}")
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """ref hapi/callbacks.py ReduceLROnPlateau: scale the optimizer lr by
    `factor` after `patience` epochs without `monitor` improvement."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.wait = 0
        self.cooldown_counter = 0
        self.best = None
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:                     # explicit: 0.0 is a real value
            cur = logs.get(f"eval_{self.monitor}")
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.cooldown_counter > 0:
            # in cooldown: no patience counting at all (ref semantics)
            self.cooldown_counter -= 1
            self.wait = 0
            return
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = self.model._optimizer
            try:
                new_lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(new_lr)
            except (RuntimeError, TypeError) as e:
                # an LRScheduler owns the lr: warn once and stand down
                import warnings
                warnings.warn(f"ReduceLROnPlateau: cannot adjust lr "
                              f"({e}); disable the scheduler to use this "
                              "callback")
                self.patience = float("inf")
                return
            if self.verbose:
                print(f"ReduceLROnPlateau: epoch {epoch}: lr -> {new_lr}")
            self.cooldown_counter = self.cooldown
            self.wait = 0


class TelemetryCallback(Callback):
    """Unified-telemetry training callback (docs/observability.md): per
    train step it feeds the `train_step_seconds` histogram and the
    `train_loss` gauge, bumps `train_steps_total`, and every
    `memory_freq` steps refreshes the PJRT device-memory gauges
    (`device_bytes_in_use` / `device_peak_bytes_in_use` /
    `device_bytes_limit`) — the training-side view on the same /metrics
    endpoint the serving engine exports.

        model.fit(data, callbacks=[callbacks.TelemetryCallback()])

    With `sampler`/`alerts` attached (utils/timeseries MetricsSampler,
    utils/anomaly AlertManager — or the process-wide installed sampler
    by default), every train step also banks a metrics-history sample
    and runs the anomaly detector set, so a step-time regression or a
    mid-run recompile fires an `alert` journal event while the run is
    still going, not in the post-mortem.
    """

    def __init__(self, memory_freq=10, device=None, sampler=None,
                 alerts=None):
        super().__init__()
        from ..utils import telemetry
        self.memory_freq = max(0, int(memory_freq))
        self.device = device
        self.sampler = sampler
        self.alerts = alerts
        self._t0 = None
        self._steps = telemetry.counter(
            "train_steps_total", "Train steps completed")
        self._step_h = telemetry.histogram(
            "train_step_seconds", "Wall time per train step")
        self._loss = telemetry.gauge(
            "train_loss", "Loss of the latest train step")
        self._mem_in_use = telemetry.gauge(
            "device_bytes_in_use", "PJRT device memory in use")
        self._mem_peak = telemetry.gauge(
            "device_peak_bytes_in_use", "PJRT peak device memory")
        self._mem_limit = telemetry.gauge(
            "device_bytes_limit", "PJRT device memory limit")

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is not None:
            self._step_h.observe(time.perf_counter() - self._t0)
            self._t0 = None
        self._steps.inc()
        loss = (logs or {}).get("loss")
        if isinstance(loss, (list, tuple)) and loss:
            loss = loss[0]
        if isinstance(loss, numbers.Number):
            self._loss.set(float(loss))
        if self.memory_freq and step % self.memory_freq == 0:
            self._poll_device_memory()
        from ..utils import timeseries
        sampler = self.sampler or timeseries.get_sampler()
        if sampler is not None:
            sampler.maybe_sample()
        if self.alerts is not None:
            self.alerts.evaluate()

    def on_train_end(self, logs=None):
        self._poll_device_memory()

    def _poll_device_memory(self):
        from ..utils import monitor
        try:
            stats = monitor.device_memory_stats(self.device)
        except Exception:      # device probe itself failed: skip
            return
        if not stats:
            # CPU-only jax: memory_stats() is None — skip the gauges
            # entirely rather than publishing misleading zeros
            return
        self._mem_in_use.set(stats.get("bytes_in_use", 0))
        self._mem_peak.set(stats.get("peak_bytes_in_use", 0))
        self._mem_limit.set(stats.get("bytes_limit", 0))


class VisualDL(Callback):
    """Stub writer: VisualDL isn't installed in this image; logs to a jsonl."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/scalars.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        if self._f:
            import json
            clean = {k: float(v) for k, v in (logs or {}).items()
                     if isinstance(v, numbers.Number)}
            self._f.write(json.dumps({"step": step, **clean}) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
