class Model:  # placeholder
    pass
def summary(*a, **k):
    raise NotImplementedError
