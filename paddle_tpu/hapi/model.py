"""hapi Model — Keras-like fit/evaluate/predict
(ref python/paddle/hapi/model.py:810 fit, :1299 predict; adapters :224,609).

The reference has separate static/dygraph adapters; here the single adapter is
jit.TrainStep: fit() compiles forward+loss+backward+update into one donated XLA
executable and streams DataLoader batches into it.
"""
import numpy as np

from ..framework.tensor import Tensor
from ..framework import state
from ..metric import Metric
from . import callbacks as cbks


def _timed_iter(loader, skip=0):
    """Yield (batch_idx, data_wait_seconds, batch): the epoch-relative
    batch index (resume fast-forward included, so the journal's
    data-wait attribution and the resume cursor agree on the same
    numbering) and how long the input pipeline made the train loop wait
    for each batch — the 'data' phase of the flight recorder's
    step-time breakdown.

    `skip` fast-forwards a resumed epoch: a reader exposing
    `iter_from` (DataLoader does) seeks — sampler draws replayed,
    dataset fetches skipped; anything else is fetched and discarded
    (always bitwise-exact). The skipped batches' wall time is
    attributed to the first yielded batch's data wait.

    `chaos.DATA_LOAD` fires before each fetch: a delay fault is a
    stalled input pipeline (watchdog territory), a raise a crashed
    reader."""
    import time
    from ..utils import chaos
    skip = max(0, int(skip))
    pending = 0.0
    if skip:
        t0 = time.perf_counter()
        if hasattr(loader, "iter_from"):
            it = loader.iter_from(skip)
        else:
            it = iter(loader)
            for _ in range(skip):
                try:
                    next(it)
                except StopIteration:
                    return
        pending = time.perf_counter() - t0
    else:
        it = iter(loader)
    idx = skip
    while True:
        if chaos.enabled():
            chaos.fire(chaos.DATA_LOAD, batch=idx)
        t0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            return
        yield idx, pending + (time.perf_counter() - t0), batch
        pending = 0.0
        idx += 1


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self.mode = "train"       # ref hapi Model.mode: train|eval|test
        self._metrics = []
        self._train_step = None
        self._flight_recorder = None
        self._scaler = None
        self._watchdog = None
        self._fit_cursor = None       # {"epoch","batch","epoch_numpy_rng"}
        self._resume_state = None     # stashed by load_latest for fit(resume=)
        self.stop_training = False

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        self._amp_configs = amp_configs
        # a GradScaler handed in through amp_configs (the instance
        # itself, or {"scaler": scaler}) joins the full-state
        # checkpoint: save() captures scale + skip counters and
        # load_latest restores them (utils/resume.py)
        from ..amp import GradScaler
        self._scaler = None
        if isinstance(amp_configs, GradScaler):
            self._scaler = amp_configs
        elif isinstance(amp_configs, dict) and \
                isinstance(amp_configs.get("scaler"), GradScaler):
            self._scaler = amp_configs["scaler"]

    def _loss_fn(self, *args):
        # split model outputs from labels by loss arity: loss(out..., label...)
        return self._loss(*args)

    # ------------------------------------------------------------- training
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, flight_recorder=None,
            resume=False, save_steps=None, watchdog=None):
        """Train. Beyond the reference surface:

        * `save_dir`/`save_freq` — per-epoch checkpoints
          (`{save_dir}/{epoch}` + `final`, via ModelCheckpoint);
          `save_steps=N` instead checkpoints every N global steps to
          unique `{save_dir}/step{n}` prefixes — the elastic-training
          cadence (per-step prefixes keep a resumable fallback when a
          re-save is torn mid-write, see Model.save).
        * `resume=True` — continue the run a prior `load_latest`
          restored: the data cursor fast-forwards to the checkpoint's
          (epoch, batch) with the epoch-start numpy RNG replayed (same
          shuffle permutation), the step counter/RNG chain/LR schedule/
          scaler continue, and a `resume` journal event records the
          prior run's id and step (`train_resumes_total` counts it).
          Kill-at-any-step → resume is bitwise-identical to the
          uninterrupted run — proven by scripts/chaos_train.py.
        * `watchdog` — True / kwargs dict / a `utils.resume.
          TrainWatchdog`: a monitor thread journals a `hang` event
          (with thread stacks) when no step completes within a multiple
          of the rolling step time (`train_watchdog_stalls_total`).
        """
        from ..io import DataLoader, Dataset
        from ..framework import state as fstate
        from ..utils import flight_recorder as fr
        from ..utils import resume as resume_mod
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        auto_cbs = []
        if save_dir and not save_steps:
            auto_cbs.append(cbks.ModelCheckpoint(save_freq, save_dir))
        cb_list = cbks.CallbackList(list(callbacks or []) + auto_cbs)
        cb_list.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        # resume target (stashed by load_latest; consumed exactly once)
        resume_info = None
        # never inherit a prior (possibly crashed) resume's provenance
        # stash — only THIS fit's resume block may arm the drift check
        self._resume_sharding = None
        if resume:
            resume_info, self._resume_state = self._resume_state, None
        start_epoch, start_batch, epoch_rng_snapshot = 0, 0, None
        if resume_info and resume_info.get("cursor"):
            cur = resume_info["cursor"]
            start_epoch = max(0, int(cur.get("epoch") or 0))
            start_batch = max(0, int(cur.get("batch") or 0))
            epoch_rng_snapshot = cur.get("epoch_numpy_rng")
        # flight recorder: a FlightRecorder, or a journal path (owned —
        # opened here, closed in the finally). docs/observability.md
        # documents the journal schema; on ANY exception the ring buffer
        # is flushed so the last steps reach disk.
        recorder, own_recorder = flight_recorder, False
        if recorder is not None and not isinstance(recorder,
                                                   fr.FlightRecorder):
            recorder = fr.FlightRecorder(recorder)
            own_recorder = True
        # the watchdog rides the flight-recorder attach path; asked for
        # without a recorder, it journals into an in-memory one (the
        # stall metric still counts)
        wd = watchdog
        if isinstance(wd, bool):
            # watchdog=True → defaults; watchdog=False → explicitly off
            wd = {} if wd else None
        if wd is not None and not isinstance(wd, resume_mod.TrainWatchdog):
            wd_kwargs = wd if isinstance(wd, dict) else {}
            wd = resume_mod.TrainWatchdog(recorder=recorder, **wd_kwargs)
        if wd is not None:
            if recorder is None and wd._recorder is None:
                recorder = fr.FlightRecorder(None)
                wd._recorder = recorder
            wd.start()
        self._watchdog = wd
        self._flight_recorder = recorder
        prev_recorder = fr.set_recorder(recorder) \
            if recorder is not None else None
        history = {"loss": []}
        it_count = 0
        logs = {}
        status, err = "ok", None
        # run_start onward lives under the try: an unwritable journal
        # path (or a callback raising in on_begin) must still restore
        # the previous current recorder in the finally
        try:
            if recorder is not None:
                recorder.run_start(mode="fit", epochs=int(epochs),
                                   steps_per_epoch=steps,
                                   batch_size=int(batch_size))
            if resume_info is not None:
                resume_mod.record_resume(
                    recorder, prior_run_id=resume_info.get("run_id"),
                    step=resume_info.get("step"), epoch=start_epoch,
                    batch=start_batch)
                # elastic reshard: a checkpoint written on a different
                # mesh journals the layout transition (the rebuilt
                # sharded step re-derives placements for the CURRENT
                # mesh on first use — utils/resume.maybe_record_reshard)
                resume_mod.maybe_record_reshard(resume_info, recorder)
                # stash the provenance so the first built step can be
                # checked against it (train_batch_parts): a resume that
                # silently loses the sharding strategy — no ZeRO, no
                # exact_reshard — would otherwise drift off the
                # checkpointed run with no sign in the journal
                self._resume_sharding = resume_info.get("sharding")
                if wd is not None:
                    # the resumed first step carries a fresh compile (a
                    # resharded step always recompiles); an EWMA warmed
                    # on the pre-kill cadence would journal it as a
                    # false hang episode
                    wd.reset_warmup()
            cb_list.on_begin("train", {"epochs": epochs, "steps": steps,
                                       "verbose": verbose,
                                       "metrics": self._metric_names()})
            completed = True
            for epoch in range(epochs):
                if epoch < start_epoch:
                    continue
                skip = start_batch if epoch == start_epoch else 0
                if skip and epoch_rng_snapshot is not None:
                    # replay the in-progress epoch's data order: the
                    # shuffle permutation (and any numpy transform
                    # draws the fast-forward replays) redraw from the
                    # SAME epoch-start RNG state the original run had
                    fstate.set_numpy_rng_state(epoch_rng_snapshot)
                epoch_rng = fstate.numpy_rng_state()
                cb_list.on_epoch_begin(epoch)
                self.network.train()
                for bidx, data_s, batch in _timed_iter(train_loader,
                                                       skip=skip):
                    cb_list.on_batch_begin("train", bidx, logs)
                    loss, metrics = self.train_batch_parts(
                        batch, data_wait=data_s, batch_idx=bidx)
                    logs = {"loss": loss, **metrics,
                            "batch_size": batch_size}
                    history["loss"].append(loss)
                    # the cursor a checkpoint records: `batch` counts
                    # batches CONSUMED this epoch — the fast-forward
                    # target of a resume
                    self._fit_cursor = {"epoch": epoch, "batch": bidx + 1,
                                        "epoch_numpy_rng": epoch_rng}
                    if save_steps and save_dir:
                        gstep = getattr(self._train_step, "_step_i",
                                        it_count + 1)
                        if gstep % int(save_steps) == 0:
                            import os
                            self.save(os.path.join(save_dir,
                                                   f"step{gstep}"))
                    cb_list.on_batch_end("train", bidx, logs)
                    it_count += 1
                    if num_iters is not None and it_count >= num_iters:
                        break
                for m in self._metrics:
                    logs[self._name_of(m)] = m.accumulate()
                    m.reset()
                cb_list.on_epoch_end(epoch, logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_data,
                                              batch_size=batch_size,
                                              verbose=0)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                if self.stop_training or (num_iters is not None
                                          and it_count >= num_iters):
                    completed = False
                    break
            if completed:
                # end-of-training cursor: a final save resumes to
                # "nothing left" instead of replaying the last epoch
                self._fit_cursor = {"epoch": int(epochs), "batch": 0,
                                    "epoch_numpy_rng":
                                        fstate.numpy_rng_state()}
            cb_list.on_end("train", logs)
            if self._train_step is not None:
                self._train_step.sync()
        except BaseException as e:
            status, err = "crashed", f"{type(e).__name__}: {e}"
            raise
        finally:
            if wd is not None:
                wd.stop()
                self._watchdog = None
            if recorder is not None:
                try:
                    recorder.run_end(status=status, error=err,
                                     steps_run=it_count)
                except Exception:
                    # already crashing: a secondary journal-write failure
                    # must not mask the original exception; on a clean
                    # run it's a real error (the journal wasn't written)
                    if status == "ok":
                        raise
                finally:
                    fr.set_recorder(prev_recorder)
                    if self._train_step is not None and hasattr(
                            self._train_step, "detach_flight_recorder"):
                        self._train_step.detach_flight_recorder()
                    if own_recorder:
                        try:
                            recorder.close()
                        except OSError:
                            pass
            self._flight_recorder = None
        return history

    def train_batch_parts(self, batch, data_wait=None, batch_idx=None):
        from ..optimizer.lr import LRScheduler
        inputs, labels = self._split_batch(batch)
        if self._train_step is None:
            from ..distributed.fleet.base import build_train_step
            self._train_step = build_train_step(
                self.network, self._loss_fn, self._optimizer,
                return_outputs=bool(self._metrics))
        recorder = getattr(self, "_flight_recorder", None)
        if recorder is not None:
            if hasattr(self._train_step, "attach_flight_recorder"):
                watchdog = getattr(self, "_watchdog", None)
                if getattr(self._train_step, "_recorder", None) \
                        is not recorder or \
                        getattr(self._train_step, "_watchdog", None) \
                        is not watchdog:
                    self._train_step.attach_flight_recorder(
                        recorder, watchdog=watchdog)
            elif not getattr(self, "_fr_unsupported_warned", False):
                import warnings
                warnings.warn(
                    f"{type(self._train_step).__name__} does not support "
                    "flight-recorder instrumentation; the journal will "
                    "carry run/checkpoint events but no step/compile/"
                    "nonfinite events", stacklevel=2)
                self._fr_unsupported_warned = True
        shard_doc = getattr(self, "_resume_sharding", None)
        if shard_doc:
            self._resume_sharding = None
            self._warn_resume_sharding_drift(shard_doc, recorder)
        if data_wait is not None and \
                hasattr(self._train_step, "set_data_wait"):
            self._train_step.set_data_wait(data_wait, batch=batch_idx)
        result = self._train_step(inputs, labels)
        has_outs = getattr(self._train_step, "return_outputs", False)
        if self._metrics and not has_outs:
            import warnings
            warnings.warn(
                f"{type(self._train_step).__name__} does not expose batch "
                f"outputs; train metrics will not be computed (loss only)",
                stacklevel=2)
            self._metrics = []
        if self._metrics and has_outs:
            loss_t, outs = result
            outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
            metric_logs = {}
            for m in self._metrics:
                res = m.compute(*outs_t, *labels)
                val = m.update(*res) if isinstance(res, tuple) \
                    else m.update(res)
                metric_logs[self._name_of(m)] = val
        else:
            loss_t = result
            metric_logs = {}
        loss = float(loss_t.numpy())
        if isinstance(self._optimizer._lr, LRScheduler):
            self._optimizer._lr.step()
        return loss, metric_logs

    def _warn_resume_sharding_drift(self, shard_doc, recorder=None):
        """The checkpoint's sharding provenance vs the step this resume
        actually REBUILT. The record is provenance, not instructions —
        nothing restores the fleet strategy for the caller — so a
        resume that dropped it (no mesh, different zero_stage, lost
        exact_reshard) still runs; but it silently forks the
        checkpointed run's layout/bitwise contract, and that must be a
        visible warning + journaled `fault`, not nothing."""
        step = self._train_step
        drift = {}
        state_fn = getattr(step, "sharding_state", None)
        if state_fn is None:
            drift["step"] = (f"sharded ({shard_doc.get('mesh')})",
                             type(step).__name__)
        else:
            now = state_fn()
            for key in ("zero_stage", "exact_reshard"):
                want = shard_doc.get(key)
                if want is not None and now.get(key) != want:
                    drift[key] = (want, now.get(key))
        if not drift:
            return
        import warnings
        desc = "; ".join(f"{k}: checkpoint={a!r} resumed={b!r}"
                         for k, (a, b) in sorted(drift.items()))
        warnings.warn(
            f"resume dropped the checkpoint's sharding configuration "
            f"({desc}) — the resumed run will not follow the "
            "checkpointed run's layout/parity contract (re-apply the "
            "fleet sharding strategy before fit(resume=True))",
            stacklevel=3)
        if recorder is not None:
            recorder.fault(kind="reshard_config_drift",
                           action="warned", **{k: list(v)
                                               for k, v in drift.items()})

    def train_batch(self, inputs, labels=None):
        """Single train step (ref hapi/model.py train_batch)."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        if self._train_step is None:
            from ..distributed.fleet.base import build_train_step
            self._train_step = build_train_step(
                self.network, self._loss_fn, self._optimizer)
        loss = self._train_step(tuple(inputs), tuple(labels))
        return [float(loss.numpy())]

    # ------------------------------------------------------------- eval/pred
    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size)
        else:
            loader = eval_data
        if self._train_step is not None:
            self._train_step.sync()
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        with state.no_grad_ctx():
            for batch in loader:
                inputs, labels = self._split_batch(batch)
                outs = self.network(*[Tensor(b) if not isinstance(b, Tensor)
                                      else b for b in inputs])
                outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
                if self._loss is not None:
                    losses.append(float(
                        self._loss_fn(*outs_t, *labels).numpy()))
                for m in self._metrics:
                    res = m.compute(*outs_t, *labels)
                    m.update(*res) if isinstance(res, tuple) else m.update(res)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[self._name_of(m)] = m.accumulate()
        self.network.train()
        return logs

    def summary(self, input_size=None, dtype=None):
        """ref hapi Model.summary -> the module-level summary() printer."""
        return summary(self.network, input_size=input_size)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size)
        else:
            loader = test_data
        if self._train_step is not None:
            self._train_step.sync()
        self.network.eval()
        outputs = []
        with state.no_grad_ctx():
            for batch in loader:
                inputs, _ = self._split_batch(batch, allow_no_label=True)
                outs = self.network(*inputs)
                outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
                outputs.append([o.numpy() for o in outs_t])
        self.network.train()
        n_out = len(outputs[0])
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        with state.no_grad_ctx():
            outs = self.network(*[Tensor(i) if not isinstance(i, Tensor)
                                  else i for i in inputs])
        self.network.train()
        outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
        return [o.numpy() for o in outs_t]

    def eval_batch(self, inputs, labels=None):
        logs = {}
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        self.network.eval()
        with state.no_grad_ctx():
            outs = self.network(*[Tensor(i) if not isinstance(i, Tensor)
                                  else i for i in inputs])
            outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
            loss = self._loss_fn(*outs_t, *[Tensor(l) if not isinstance(l, Tensor)
                                            else l for l in labels])
        self.network.train()
        return [float(loss.numpy())]

    # ------------------------------------------------------------- save/load
    def save(self, path, training=True):
        """Crash-safe FULL-STATE checkpoint: each file is written
        atomically (framework.serialization: temp + fsync + os.replace)
        and the directory's `latest.json` manifest — which records each
        file's sha256 — is updated only after EVERY file landed. A
        training save captures three files under one manifest entry:
        `.pdparams` (params + buffers), `.pdopt` (optimizer
        accumulators, global step, LR-scheduler state), and `.pdtrain`
        (utils/resume.py: the PRNG chain, numpy RNG, data cursor,
        GradScaler state, prior run id) — everything `load_latest` +
        `fit(resume=True)` need to continue the EXACT trajectory.

        A crash mid-save over a FRESH prefix leaves the previous
        checkpoint loadable via `load_latest`; a crash while re-saving
        over an EXISTING prefix (old bytes already overwritten in
        place) is detected by the digest check and `load_latest`
        refuses the torn set rather than silently mixing saves — use
        unique per-step prefixes (`fit(save_steps=N)` does) when a
        resumable fallback is required."""
        import os
        from ..framework import serialization
        from ..utils import flight_recorder as fr
        from ..utils import resume as resume_mod
        if self._train_step is not None:
            self._train_step.sync()
        step = getattr(self._train_step, "_step_i", None)
        if step is None and self._optimizer is not None:
            step = self._optimizer._global_step or None
        base = os.path.basename(path)
        files = {base + ".pdparams":
                 serialization.save(dict(self.network.state_dict()),
                                    path + ".pdparams")}
        if training and self._optimizer is not None:
            files[base + ".pdopt"] = serialization.save(
                self._optimizer.state_dict(), path + ".pdopt")
        elif os.path.exists(path + ".pdopt"):
            # params-only save over a prefix that previously had an
            # optimizer file: the stale .pdopt belongs to DIFFERENT
            # params now — remove it so load()/load_latest can never
            # pair the new params with old optimizer moments
            os.unlink(path + ".pdopt")
        recorder = fr.get_recorder()
        if training:
            # a sharded step records its placement provenance (mesh
            # shape, dp_axis, zero_stage, per-leaf PartitionSpecs) in
            # the .pdtrain payload — what an elastic reshard journals
            # against; single-chip steps record None
            sharding_fn = getattr(self._train_step, "sharding_state",
                                  None)
            doc = resume_mod.capture_train_state(
                cursor=self._fit_cursor, step=step, scaler=self._scaler,
                run_id=None if recorder is None else recorder.run_id,
                sharding=None if sharding_fn is None else sharding_fn())
            files[base + ".pdtrain"] = serialization.save(
                doc, path + ".pdtrain")
        elif os.path.exists(path + ".pdtrain"):
            # same staleness rule as .pdopt: a params-only re-save must
            # not leave a prior save's RNG/cursor pretending to belong
            # to these params
            os.unlink(path + ".pdtrain")
        serialization.write_manifest(path, step=step, files=files)
        if recorder is not None:
            recorder.checkpoint(path=path, step=step, complete=True)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.serialization import load as _load
        sd = _load(path + ".pdparams")
        self.network.set_state_dict(sd)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))
        self._train_step = None  # recompile against restored state

    def load_latest(self, directory, restore_train_state=True, **kw):
        """Resume from the newest COMPLETE checkpoint in `directory`
        (the `latest.json` manifest save() maintains — a checkpoint
        whose save crashed mid-write is never listed there, and the
        manifest's sha256 digests are verified against the files on
        disk before loading). Returns the checkpoint prefix loaded, or
        None when the directory holds no manifest or the listed files
        are torn relative to it (crash while re-saving a reused
        prefix).

        When the checkpoint carries a `.pdtrain` train-state file (a
        training save) and `restore_train_state` is True, the process
        RNG chains and the Model's GradScaler are restored IN PLACE
        and the data cursor is stashed for the next
        `fit(resume=True)` — the exact-resume path
        (utils/resume.py, proven by scripts/chaos_train.py)."""
        import os
        from ..framework import serialization
        from ..utils import resume as resume_mod
        prefix = serialization.latest_checkpoint(directory)
        if prefix is None:
            return None
        doc = serialization.read_manifest(directory)
        listed = set((doc or {}).get("files") or {})
        base = os.path.basename(prefix)
        if base + ".pdopt" not in listed:
            # an on-disk .pdopt the manifest does not list is a stray
            # from some OTHER save (legacy writer, partial cleanup) —
            # verification never covered it, so it must not be paired
            # with these params
            kw["reset_optimizer"] = True
        self.load(prefix, **kw)
        self._resume_state = None
        state_path = prefix + ".pdtrain"
        if restore_train_state and base + ".pdtrain" in listed \
                and os.path.exists(state_path):
            state_doc = serialization.load(state_path)
            self._resume_state = resume_mod.apply_train_state(
                state_doc, scaler=self._scaler)
        return prefix

    def parameters(self):
        return self.network.parameters()

    # ------------------------------------------------------------- helpers
    def _split_batch(self, batch, allow_no_label=False):
        if isinstance(batch, dict):
            batch = list(batch.values())
        batch = list(batch)
        n_labels = len(self._labels) if self._labels else 1
        if allow_no_label and len(batch) == 1:
            return batch, []
        inputs = batch[:-n_labels] if len(batch) > n_labels else batch[:1]
        labels = batch[len(inputs):]
        return inputs, labels

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            names.append(self._name_of(m))
        return names

    @staticmethod
    def _name_of(m):
        n = m.name()
        return n if isinstance(n, str) else n[0]


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary analog."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, p.shape, n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = ["-" * (width + 30)]
    for name, shp, n in rows:
        lines.append(f"{name:<{width}}{str(shp):<20}{n:>10,}")
    lines.append("-" * (width + 30))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
