from .model import Model, summary
from . import callbacks
