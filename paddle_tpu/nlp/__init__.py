"""paddle_tpu.nlp — transformer language models for the BASELINE configs
(BERT-base pretraining = config 2, GPT-2 medium = config 3; the reference
ships these as test models dist_transformer.py / the nn.Transformer stack)."""
from .gpt import (GPTModel, GPTForPretraining, GPTConfig, gpt2_small,
                  gpt2_medium, gpt_generate, generate)
from .bert import BertModel, BertForPretraining, BertConfig, bert_base, bert_large
from .llama import (LlamaModel, LlamaForCausalLM, LlamaConfig,
                    llama_pretrain_loss)
