"""BERT encoder (BASELINE config 2: BERT-base pretraining, Fleet collective DP).

Built on the nn.Transformer encoder stack (post-norm like the original BERT)
with MLM + NSP pretraining heads; flash attention handles the padding mask
via the additive-mask XLA path.
"""
import math

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed import mesh as mesh_mod


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_seq_len=512,
                 type_vocab_size=2, dropout=0.1, attn_dropout=0.1,
                 initializer_range=0.02, use_recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute


def bert_base(**kw):
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072, **kw)


def bert_large(**kw):
    return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096, **kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len,
                                                cfg.hidden_size,
                                                weight_attr=init)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(cfg.dropout)
        self.word_embeddings.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as pt
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = pt.arange(s, dtype="int32").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = pt.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.transformer.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu",
            attn_dropout=cfg.attn_dropout, act_dropout=0.0,
            weight_attr=nn.ParamAttr(
                initializer=I.Normal(0.0, cfg.initializer_range)))
        self.encoder = nn.transformer.TransformerEncoder(enc_layer,
                                                         cfg.num_layers)
        self.pooler_dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [B,S] 1/0 -> additive [B,1,1,S]
            from ..ops.manipulation import cast, unsqueeze
            m = cast(attention_mask, "float32")
            mask = (1.0 - m.unsqueeze(1).unsqueeze(2)) * -1e9
        seq_out = self.encoder(x, src_mask=mask)
        pooled = F.tanh(self.pooler_dense(seq_out[:, 0]))
        return seq_out, pooled


class BertPretrainingHeads(nn.Layer):
    def __init__(self, cfg: BertConfig, embedding_weights):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.decoder_weight = embedding_weights          # tied
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output):
        from ..ops.math import matmul
        h = self.layer_norm(F.gelu(self.transform(sequence_output)))
        mlm_logits = matmul(h, self.decoder_weight,
                            transpose_y=True) + self.decoder_bias
        nsp_logits = self.seq_relationship(pooled_output)
        return mlm_logits, nsp_logits


class BertForPretraining(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.cls = BertPretrainingHeads(
            cfg, self.bert.embeddings.word_embeddings.weight)
        self.cfg = cfg

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.cls(seq_out, pooled)


def bert_pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels):
    """MLM loss over non -100 positions + NSP loss."""
    b, s, v = mlm_logits.shape
    mlm = F.cross_entropy(mlm_logits.reshape([b * s, v]),
                          mlm_labels.reshape([b * s]), ignore_index=-100)
    nsp = F.cross_entropy(nsp_logits, nsp_labels)
    return mlm + nsp
