"""LLaMA-family decoder LM — RMSNorm + RoPE + SwiGLU + grouped-query
attention. Third NLP model family next to GPT/BERT (the reference era
predates LLaMA; this is the modern-LLM surface a switching user expects,
built on the same TPU-native kernel/parallelism substrate).

TPU-first choices:
  - fused QKV projection sized for GQA (q heads + 2 * kv heads in one
    MXU matmul); KV heads are repeated with a reshape-broadcast (free
    under XLA) to feed the shared flash kernel
  - RoPE applied in f32 with precomputed cos/sin tables (static shapes)
  - causal Pallas flash attention (ops/pallas) for the [B,H,S,D] core
  - Megatron TP hints: QKV column-parallel, out row-parallel, SwiGLU
    gate/up column-parallel, down row-parallel (over 'mp')
"""
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed import mesh as mesh_mod


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=768,
                 intermediate_size=None, num_layers=12, num_heads=12,
                 num_kv_heads=None, max_seq_len=2048, rope_theta=10000.0,
                 rms_eps=1e-6, initializer_range=0.02,
                 use_recompute=False, tie_embeddings=True,
                 attn_layout=None, fused_head_loss=None,
                 attn_window=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        # LLaMA sizing: 2/3 * 4h rounded; callers may pass exact values
        self.intermediate_size = intermediate_size or int(8 * hidden_size / 3)
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads   # GQA when smaller
        self.max_seq_len = max_seq_len
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute
        # attention kernel layout (same knob as GPTConfig): "bshd"
        # (default) keeps [B,S,H,D] end to end — no layout transposes
        import os as _os
        self.attn_layout = (attn_layout
                            or _os.environ.get("PT_ATTN_LAYOUT", "bshd"))
        # vocab-chunked fused LM-head+CE, same AUTO semantics as
        # GPTConfig.fused_head_loss (None = by logits size)
        self.fused_head_loss = (None if fused_head_loss is None
                                else bool(fused_head_loss))
        # causal sliding-window attention (LLaMA + GQA + window = the
        # Mistral recipe); the banded flash kernel skips out-of-band KV
        # blocks in training and the decode band matches (see
        # cached_decode_attention)
        self.attn_window = None if attn_window is None else int(attn_window)
        self.tie_embeddings = tie_embeddings
        if num_heads % self.num_kv_heads:
            raise ValueError(f"num_heads {num_heads} not divisible by "
                             f"num_kv_heads {self.num_kv_heads}")


def _rms_norm_raw(x_, w, eps=1e-6):
    xf = x_.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32)).astype(x_.dtype)


from ..ops.dispatch import register_op as _register_op  # noqa: E402
_register_op("rms_norm", _rms_norm_raw)


class RMSNorm(nn.Layer):
    """Root-mean-square norm (no mean subtraction, no bias): stats in f32."""

    def __init__(self, dim, eps=1e-6):
        super().__init__()
        self.eps = eps
        self.weight = self.create_parameter(
            [dim], default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ..ops.dispatch import apply
        return apply(_rms_norm_raw, (x, self.weight),
                     {"eps": float(self.eps)}, name="rms_norm")


@functools.lru_cache(maxsize=8)
def rope_tables(seq_len, head_dim, theta=10000.0):
    """cos/sin tables [S, D/2] in f32. lru-cached so every attention
    layer of a model shares ONE table (not per-layer copies baked into
    the traced program)."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(seq_len)
    freqs = np.outer(t, inv)                       # [S, D/2]
    return (jnp.asarray(np.cos(freqs), jnp.float32),
            jnp.asarray(np.sin(freqs), jnp.float32))


def _rotate_pairs(x, c, sn):
    """Rotate interleaved pairs (x[2i], x[2i+1]) of x's last dim by
    cos/sin rows c/sn (broadcastable to [..., D/2]) in f32, cast back —
    the one place the pair-layout convention lives."""
    d = x.shape[-1]
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], d // 2, 2)
    x1, x2 = xf[..., 0], xf[..., 1]
    y1 = x1 * c - x2 * sn
    y2 = x1 * sn + x2 * c
    return jnp.stack([y1, y2], axis=-1).reshape(x.shape).astype(x.dtype)


def _rope_rotate(x, cos, sin, pos_offset, head_axis):
    """Shared RoPE core over a contiguous position range.
    head_axis selects the layout — 1 for [B,H,S,D], 2 for [B,S,H,D]; the
    sequence axis is the other one. A static pos_offset is range-checked
    (a traced offset can't be; dynamic_slice would clamp silently)."""
    d = x.shape[-1]
    seq_axis = 3 - head_axis            # the non-head middle axis
    s_len = x.shape[seq_axis]
    if isinstance(pos_offset, int) and pos_offset + s_len > cos.shape[0]:
        raise ValueError(
            f"RoPE positions [{pos_offset}, {pos_offset + s_len}) exceed "
            f"the table length {cos.shape[0]} (raise max_seq_len)")
    c = jax.lax.dynamic_slice_in_dim(cos, pos_offset, s_len, axis=0)
    sn = jax.lax.dynamic_slice_in_dim(sin, pos_offset, s_len, axis=0)
    bshape = [1, 1, 1, d // 2]
    bshape[seq_axis] = s_len
    return _rotate_pairs(x, c.reshape(bshape), sn.reshape(bshape))


def apply_rope_bshd(x, cos, sin, pos_offset=0):
    """x: [B, S, H, D] (transpose-free layout)."""
    return _rope_rotate(x, cos, sin, pos_offset, head_axis=2)


def apply_rope(x, cos, sin, pos_offset=0):
    """x: [B, H, S, D] array (default layout)."""
    return _rope_rotate(x, cos, sin, pos_offset, head_axis=1)


def apply_rope_positions(x, cos, sin, positions):
    """x: [B, H, C, D] rotated at traced absolute positions — a [C]
    vector (chunked prefill: one lane, every row at the same offsets)
    or a [B, C] matrix (the speculative verify wave: every lane's
    k+1-token span starts at its own depth). GATHERED per element, not
    dynamic-sliced: a final padded chunk can run past the table end,
    where a dynamic_slice clamps its START and silently shifts the
    rotation of VALID rows; the gather clamps only the out-of-range pad
    rows themselves (whose K/V is redirected to the scratch block and
    never read)."""
    idx = jnp.minimum(positions, cos.shape[0] - 1)
    if jnp.ndim(positions) == 2:                    # [B, C] per-lane
        c = cos[idx][:, None, :, :]                 # [B, 1, C, D/2]
        sn = sin[idx][:, None, :, :]
    else:
        c = cos[idx][None, None, :, :]              # [1, 1, C, D/2]
        sn = sin[idx][None, None, :, :]
    return _rotate_pairs(x, c, sn)


def apply_rope_at(x, cos, sin, pos):
    """Single-token RoPE at a per-row position VECTOR. x: [B, H, 1, D];
    pos: [B] int — each batch row rotated at its own position (slot-wise
    serving decode, where slots sit at different depths). The table rows
    come from one gather cos[pos] instead of a dynamic_slice, so the
    whole batch stays one fused program."""
    return _rotate_pairs(x, cos[pos][:, None, None, :],
                         sin[pos][:, None, None, :])


@functools.lru_cache(maxsize=8)
def _rope_tensor_tables(seq_len, head_dim, theta):
    """Tensor wrappers for the rope tables, cached so EVERY layer of a
    captured model dedupes onto one shared const pair in the desc."""
    from ..framework.tensor import Tensor
    cos, sin = rope_tables(seq_len, head_dim, theta)
    t_cos, t_sin = Tensor(cos), Tensor(sin)
    t_cos.stop_gradient = True
    t_sin.stop_gradient = True
    return t_cos, t_sin


def _split_rope_bshd(a, cos, sin, nh, nkv, hd):
    """Split a fused qkv projection [B, S, (nh+2*nkv)*hd] and apply RoPE
    to q/k in the transpose-free bshd layout (v reshape only). One home
    for the split/rope convention — shared by the training forward
    (_llama_attention_raw) and the serving prefill path."""
    b, s = a.shape[0], a.shape[1]
    q, k, v = jnp.split(a, [nh * hd, (nh + nkv) * hd], axis=-1)
    q = apply_rope_bshd(q.reshape(b, s, nh, hd), cos, sin)
    k = apply_rope_bshd(k.reshape(b, s, nkv, hd), cos, sin)
    return q, k, v.reshape(b, s, nkv, hd)


def _gqa_flash_bshd(q, k, v, nh, nkv, window):
    """GQA kv-head repeat (free reshape-broadcast under XLA) + causal
    flash attention, bshd layout."""
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from ..ops.pallas.flash_attention import _flash_array
    return _flash_array(q, k, v, causal=True, layout="bshd", window=window)


def _llama_attention_raw(x, wqkv, cos, sin, num_heads=1, num_kv_heads=1,
                         head_dim=1, attn_layout="bhsd", window=None):
    """Registered (desc-serializable) GQA attention: fused qkv matmul,
    RoPE from the cos/sin table inputs, kv-head repeat, causal flash.
    The rope tables ride as const inputs so captured LLaMA programs
    replay in fresh processes. attn_layout="bshd" keeps [B,S,H,D]
    end-to-end (RoPE + kv-repeat + packed-lane kernel) — zero layout
    transposes in the whole attention block."""
    nh, nkv, hd = num_heads, num_kv_heads, head_dim
    cos = jax.lax.stop_gradient(cos)
    sin = jax.lax.stop_gradient(sin)
    b, s, _ = x.shape
    qkv = x @ wqkv                                   # [B,S,(nh+2kv)*hd]
    from ..ops.pallas.flash_attention import _flash_array
    if attn_layout == "bshd":
        q, k, v = _split_rope_bshd(qkv, cos, sin, nh, nkv, hd)
        o = _gqa_flash_bshd(q, k, v, nh, nkv, window)
        return o.reshape(b, s, nh * hd)
    q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
    q = apply_rope(q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3), cos, sin)
    k = apply_rope(k.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3), cos, sin)
    v = v.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3)
    if nkv != nh:                                    # GQA: repeat KV
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    o = _flash_array(q, k, v, causal=True, window=window)
    return o.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)


_register_op("llama_attention", _llama_attention_raw)


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = h // cfg.num_heads
        self.attn_layout = getattr(cfg, "attn_layout", "bshd")
        self.attn_window = getattr(cfg, "attn_window", None)
        init = I.Normal(0.0, cfg.initializer_range)
        qkv_out = (cfg.num_heads + 2 * cfg.num_kv_heads) * self.head_dim
        self.qkv_proj = nn.Linear(h, qkv_out, bias_attr=False,
                                  weight_attr=nn.ParamAttr(initializer=init))
        self.o_proj = nn.Linear(cfg.num_heads * self.head_dim, h,
                                bias_attr=False,
                                weight_attr=nn.ParamAttr(
                                    initializer=I.Normal(
                                        0.0, cfg.initializer_range
                                        / math.sqrt(2 * cfg.num_layers))))
        self.qkv_proj.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.o_proj.weight.sharding = P(mesh_mod.MP_AXIS, None)
        self._rope_args = (cfg.max_seq_len, self.head_dim,
                           cfg.rope_theta)
        self._cos, self._sin = rope_tables(cfg.max_seq_len, self.head_dim,
                                           cfg.rope_theta)

    def forward(self, x):
        from ..ops.dispatch import apply
        t_cos, t_sin = _rope_tensor_tables(self._rope_args[0],
                                           self._rope_args[1],
                                           self._rope_args[2])
        out = apply(_llama_attention_raw,
                    (x, self.qkv_proj.weight, t_cos, t_sin),
                    {"num_heads": self.num_heads,
                     "num_kv_heads": self.num_kv_heads,
                     "head_dim": self.head_dim,
                     "attn_layout": self.attn_layout,
                     "window": (None if self.attn_window is None
                                else int(self.attn_window))},
                    name="llama_attention")
        return self.o_proj(out)

    # -------------------------------------------------- incremental decode
    def init_cache(self, batch, max_len, dtype=jnp.float32):
        """KV cache [B, kv_heads, L, head_dim] x2 — GQA caches only the
        kv heads (the memory win that motivates GQA at decode time).
        max_len is validated against the RoPE table here because inside
        the decode loop `pos` is traced and apply_rope's static range
        check cannot fire (dynamic_slice would clamp silently)."""
        if max_len > self._cos.shape[0]:
            raise ValueError(
                f"decode length {max_len} exceeds the RoPE table "
                f"({self._cos.shape[0]}); raise max_seq_len")
        shape = (batch, self.num_kv_heads, max_len, self.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def init_paged_cache(self, num_blocks, block_size, dtype=jnp.float32):
        """Block-pool KV cache [num_blocks, kv_heads, block_size, hd] x2
        — GQA pools cache only the kv heads, and requests claim blocks
        through a host-managed table (serving/paged)."""
        shape = (num_blocks, self.num_kv_heads, block_size, self.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def decode(self, x_t, cache, pos, block_tables=None):
        """One-token step: RoPE at `pos` (traced), write K/V, attend over
        cache[:pos]. x_t: [B, 1, H] Tensor. `pos` is a scalar (lockstep
        batch) or a [B] vector — slot-wise serving decode where each row
        is at its own depth; the vector path scatters per-row cache
        writes and masks per-row, same fixed shapes, one program. With
        block_tables [B, nblk] the cache is the block POOL: K/V scatter
        through the table and attention reads the gathered per-row
        view."""
        from ..framework.tensor import Tensor
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        b = x_t.shape[0]
        qkv = self.qkv_proj(x_t)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        q, k_t, v_t = jnp.split(a, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = q.reshape(b, 1, nh, hd).transpose(0, 2, 1, 3)
        k_t = k_t.reshape(b, 1, nkv, hd).transpose(0, 2, 1, 3)
        v_t = v_t.reshape(b, 1, nkv, hd).transpose(0, 2, 1, 3)
        ck, cv = cache
        from ..nn.paged_attention import paged_decode_attention
        from ..nn.transformer import (cached_decode_attention,
                                      scatter_block_kv_at, scatter_kv_at)
        if block_tables is not None:
            # fused path: attention reads K/V straight out of the pool
            # through the table (dispatch: reference | lax | pallas) —
            # the [B, Hkv, nblk*BS, D] gathered view never exists
            q = apply_rope_at(q, self._cos, self._sin, pos)
            k_t = apply_rope_at(k_t, self._cos, self._sin, pos)
            ck = scatter_block_kv_at(ck, k_t, block_tables, pos)
            cv = scatter_block_kv_at(cv, v_t, block_tables, pos)
            out = paged_decode_attention(q, ck, cv, block_tables, pos,
                                         1.0 / math.sqrt(hd),
                                         window=self.attn_window)
        else:
            if jnp.ndim(pos):
                q = apply_rope_at(q, self._cos, self._sin, pos)
                k_t = apply_rope_at(k_t, self._cos, self._sin, pos)
                ck = scatter_kv_at(ck, k_t, pos)
                cv = scatter_kv_at(cv, v_t, pos)
            else:
                q = apply_rope(q, self._cos, self._sin, pos_offset=pos)
                k_t = apply_rope(k_t, self._cos, self._sin,
                                 pos_offset=pos)
                ck = jax.lax.dynamic_update_slice_in_dim(
                    ck, k_t.astype(ck.dtype), pos, axis=2)
                cv = jax.lax.dynamic_update_slice_in_dim(
                    cv, v_t.astype(cv.dtype), pos, axis=2)
            out = cached_decode_attention(q, ck, cv, pos,
                                          1.0 / math.sqrt(hd),
                                          window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, nh * hd)
        out = self.o_proj(Tensor(out.astype(x_t._data.dtype)))
        return out, (ck, cv)

    def prefill_chunk(self, x, cache, block_tables, chunk_start,
                      valid_len):
        """One prompt chunk [1, C, H] against the block pool: RoPE at the
        absolute positions chunk_start + arange(C) (gathered per
        position — a final chunk may overrun the table with pad rows),
        scatter the chunk's K/V through the table, attend the C queries
        over the gathered view (previous chunks + own causal prefix)."""
        from ..framework.tensor import Tensor
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        q, k, v = jnp.split(a, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3)
        positions = chunk_start + jnp.arange(s)
        q = apply_rope_positions(q, self._cos, self._sin, positions)
        k = apply_rope_positions(k, self._cos, self._sin, positions)
        ck, cv = cache
        from ..nn.paged_attention import paged_chunk_attention
        from ..nn.transformer import scatter_block_kv_chunk
        ck = scatter_block_kv_chunk(ck, k, block_tables, positions,
                                    valid_len)
        cv = scatter_block_kv_chunk(cv, v, block_tables, positions,
                                    valid_len)
        out = paged_chunk_attention(q, ck, cv, block_tables, chunk_start,
                                    1.0 / math.sqrt(hd),
                                    window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, nh * hd)
        out = self.o_proj(Tensor(out.astype(x._data.dtype)))
        return out, (ck, cv)

    def decode_chunk(self, x, cache, block_tables, start, valid_len):
        """Speculative verify step: C tokens for EVERY lane at once.
        x: [S, C, H]; block_tables: [S, nblk]; start/valid_len: [S] —
        lane s's tokens sit at absolute positions start[s] + i, with
        writes at i >= valid_len[s] redirected to the scratch block
        (horizon / per-request spec_len clamp). RoPE is gathered at the
        per-lane position matrix, K/V scatter through every lane's
        table in one op (scatter_block_kv_chunk_batched), and
        chunk_attention's vector-start mask gives each query row its
        own causal frontier — the C==1 case of this IS the decode wave,
        which is why verify is a third compiled program, not a new
        attention path."""
        from ..framework.tensor import Tensor
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        q, k, v = jnp.split(a, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nkv, hd).transpose(0, 2, 1, 3)
        positions = start[:, None] + jnp.arange(s)[None, :]    # [S, C]
        q = apply_rope_positions(q, self._cos, self._sin, positions)
        k = apply_rope_positions(k, self._cos, self._sin, positions)
        ck, cv = cache
        from ..nn.paged_attention import paged_chunk_attention
        from ..nn.transformer import scatter_block_kv_chunk_batched
        ck = scatter_block_kv_chunk_batched(ck, k, block_tables, start,
                                            valid_len)
        cv = scatter_block_kv_chunk_batched(cv, v, block_tables, start,
                                            valid_len)
        out = paged_chunk_attention(q, ck, cv, block_tables, start,
                                    1.0 / math.sqrt(hd),
                                    window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, nh * hd)
        out = self.o_proj(Tensor(out.astype(x._data.dtype)))
        return out, (ck, cv)

    def prefill(self, x, cache):
        """Prompt-phase step: the training forward's attention math over
        x [B, P, H], additionally writing the prompt's K/V into
        cache[:, :, :P] so decode can continue at pos=P. Positions past
        the true prompt length hold garbage until the decode frontier
        overwrites them — cached_decode_attention masks ks<=pos, so a
        not-yet-rewritten cell is never attended. P is static (the engine
        pads prompts to one bucket) => one compiled prefill program."""
        from ..framework.tensor import Tensor
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        q, k, v = _split_rope_bshd(a, self._cos, self._sin, nh, nkv, hd)
        ck, cv = cache
        ck = jax.lax.dynamic_update_slice(
            ck, jnp.transpose(k, (0, 2, 1, 3)).astype(ck.dtype),
            (0, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cv, jnp.transpose(v, (0, 2, 1, 3)).astype(cv.dtype),
            (0, 0, 0, 0))
        o = _gqa_flash_bshd(q, k, v, nh, nkv, self.attn_window)
        out = self.o_proj(Tensor(
            o.reshape(b, s, nh * hd).astype(x._data.dtype)))
        return out, (ck, cv)


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        h, m = cfg.hidden_size, cfg.intermediate_size
        self.gate_proj = nn.Linear(h, m, bias_attr=False,
                                   weight_attr=nn.ParamAttr(initializer=init))
        self.up_proj = nn.Linear(h, m, bias_attr=False,
                                 weight_attr=nn.ParamAttr(initializer=init))
        self.down_proj = nn.Linear(m, h, bias_attr=False,
                                   weight_attr=nn.ParamAttr(
                                       initializer=I.Normal(
                                           0.0, cfg.initializer_range
                                           / math.sqrt(2 * cfg.num_layers))))
        self.gate_proj.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.up_proj.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.down_proj.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x

    def decode(self, x, cache, pos, block_tables=None):
        a, cache = self.self_attn.decode(self.input_layernorm(x), cache,
                                         pos, block_tables=block_tables)
        x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, cache

    def prefill(self, x, cache):
        a, cache = self.self_attn.prefill(self.input_layernorm(x), cache)
        x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, cache

    def prefill_chunk(self, x, cache, block_tables, chunk_start,
                      valid_len):
        a, cache = self.self_attn.prefill_chunk(
            self.input_layernorm(x), cache, block_tables, chunk_start,
            valid_len)
        x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, cache

    def decode_chunk(self, x, cache, block_tables, start, valid_len):
        a, cache = self.self_attn.decode_chunk(
            self.input_layernorm(x), cache, block_tables, start,
            valid_len)
        x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, cache


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.embed_tokens.weight.sharding = P(mesh_mod.MP_AXIS, None)
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        if self.cfg.use_recompute:
            from ..incubate.recompute import recompute
            for blk in self.layers:
                x = recompute(blk, x)
        else:
            for blk in self.layers:
                x = blk(x)
        return self.norm(x)

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        return [blk.self_attn.init_cache(batch, max_len, dtype)
                for blk in self.layers]

    def init_paged_cache(self, num_blocks, block_size, max_len,
                         dtype=jnp.float32):
        """Per-layer block pools [num_blocks, kv_heads, block_size, hd]
        x2. max_len (= nblk * block_size, the per-request horizon) is
        validated against the RoPE table here because positions are
        traced inside the programs (dynamic_slice would clamp
        silently)."""
        first = self.layers[0].self_attn
        if max_len > first._cos.shape[0]:
            raise ValueError(
                f"decode length {max_len} exceeds the RoPE table "
                f"({first._cos.shape[0]}); raise max_seq_len")
        return [blk.self_attn.init_paged_cache(num_blocks, block_size,
                                               dtype)
                for blk in self.layers]

    def decode_step(self, tok, caches, pos, block_tables=None):
        """tok: [B, 1] ids; pos: traced position — a scalar, or a [B]
        vector for slot-wise serving decode. With block_tables [B, nblk]
        the caches are block POOLS (paged serving engine). Returns
        (h, caches)."""
        from ..framework.tensor import Tensor
        pos = pos._data if isinstance(pos, Tensor) else pos
        x = self.embed_tokens(tok)
        new_caches = []
        for blk, cache in zip(self.layers, caches):
            x, cache = blk.decode(x, cache, pos,
                                  block_tables=block_tables)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def prefill_chunk(self, tok_chunk, caches, block_tables, chunk_start,
                      valid_len):
        """One prompt chunk [1, C] ids at absolute positions chunk_start
        + arange(C) against the block pools (chunked prefill)."""
        x = self.embed_tokens(tok_chunk)
        new_caches = []
        for blk, cache in zip(self.layers, caches):
            x, cache = blk.prefill_chunk(x, cache, block_tables,
                                         chunk_start, valid_len)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def decode_chunk(self, tok_chunk, caches, block_tables, start,
                     valid_len):
        """Speculative verify: C tokens per lane ([S, C] ids) at
        per-lane absolute positions start[s] + i against the block
        pools. Returns (h [S, C, Hd], caches)."""
        from ..framework.tensor import Tensor
        block_tables = (block_tables._data
                        if isinstance(block_tables, Tensor)
                        else block_tables)
        start = start._data if isinstance(start, Tensor) else start
        valid_len = (valid_len._data if isinstance(valid_len, Tensor)
                     else valid_len)
        x = self.embed_tokens(tok_chunk)
        new_caches = []
        for blk, cache in zip(self.layers, caches):
            x, cache = blk.decode_chunk(x, cache, block_tables, start,
                                        valid_len)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def prefill(self, input_ids, max_len, dtype=jnp.float32):
        """Prompt-phase forward over [B, P] ids that also populates fresh
        [B, kv_heads, max_len, head_dim] KV caches for positions [0, P).
        Returns (hidden, caches) — decode continues at pos=P."""
        x = self.embed_tokens(input_ids)
        caches = self.init_cache(input_ids.shape[0], max_len, dtype)
        new_caches = []
        for blk, cache in zip(self.layers, caches):
            x, cache = blk.prefill(x, cache)
            new_caches.append(cache)
        return self.norm(x), new_caches


class LlamaForCausalLM(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(
                cfg.hidden_size, cfg.vocab_size, bias_attr=False,
                weight_attr=nn.ParamAttr(
                    initializer=I.Normal(0.0, cfg.initializer_range)))
            self.lm_head.weight.sharding = P(None, mesh_mod.MP_AXIS)

    def _logits(self, hidden):
        if self.cfg.tie_embeddings:
            w = self.model.embed_tokens.weight
            from ..ops.math import matmul
            return matmul(hidden, w, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids):
        hidden = self.model(input_ids)
        logits = self._logits(hidden)
        from .gpt import _use_fused_head
        if (self.cfg.tie_embeddings
                and _use_fused_head(self.cfg, logits.shape)):
            # hand the loss the pre-head pieces so llama_pretrain_loss
            # (-> gpt_pretrain_loss) takes the vocab-chunked fused CE and
            # the dense head matmul above DCEs under jit. ARRAY snapshot
            # of w for the same functional_call reason as GPT (gpt.py):
            # the Tensor's _data is restored after tracing.
            w = self.model.embed_tokens.weight
            logits._fused_head = (hidden, w, w._data)
        return logits

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        return self.model.init_cache(batch, max_len, dtype)

    def init_paged_cache(self, num_blocks, block_size, max_len,
                         dtype=jnp.float32):
        return self.model.init_paged_cache(num_blocks, block_size,
                                           max_len, dtype)

    def decode_step(self, tok, caches, pos, block_tables=None):
        h, caches = self.model.decode_step(tok, caches, pos,
                                           block_tables=block_tables)
        return self._logits(h), caches

    def decode_chunk(self, tok_chunk, caches, block_tables, start,
                     valid_len):
        """Speculative verify: logits for ALL C positions of every lane
        ([S, C, V] — the k+1-proportional head cost the verify program
        pays on purpose: one batched forward scores the whole drafted
        span)."""
        h, caches = self.model.decode_chunk(tok_chunk, caches,
                                            block_tables, start,
                                            valid_len)
        return self._logits(h), caches

    def prefill_chunk(self, tok_chunk, caches, block_tables, chunk_start,
                      valid_len, frontier=None):
        """One prompt chunk against the block pools; frontier (traced
        index within the chunk) keeps the vocab matmul [1, V] — only the
        final chunk's frontier row is consumed by the serving engine."""
        from ..framework.tensor import Tensor
        h, caches = self.model.prefill_chunk(tok_chunk, caches,
                                             block_tables, chunk_start,
                                             valid_len)
        if frontier is not None:
            hr = h._data if isinstance(h, Tensor) else h
            h = Tensor(jax.lax.dynamic_slice_in_dim(hr, frontier, 1,
                                                    axis=1))
        return self._logits(h), caches

    def prefill(self, input_ids, max_len, dtype=jnp.float32,
                frontier=None):
        """frontier (traced index): return logits only for that prompt
        position — the serving engine wants ONE next-token row, and
        indexing before the LM head keeps the vocab matmul [1, V]
        instead of [P, V] (P = padded bucket)."""
        from ..framework.tensor import Tensor
        h, caches = self.model.prefill(input_ids, max_len, dtype)
        if frontier is not None:
            hr = h._data if isinstance(h, Tensor) else h
            h = Tensor(jax.lax.dynamic_slice_in_dim(hr, frontier, 1,
                                                    axis=1))
        return self._logits(h), caches


def llama_pretrain_loss(logits, labels):
    """Same label-shift CE as GPT (see gpt.gpt_pretrain_loss)."""
    from .gpt import gpt_pretrain_loss
    return gpt_pretrain_loss(logits, labels)
