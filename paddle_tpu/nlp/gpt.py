"""GPT decoder-only LM (BASELINE config 3: GPT-2 medium with pipeline + tensor
parallel + recompute).

TPU-first design decisions:
  - fused QKV projection (one MXU matmul instead of three)
  - causal flash attention (Pallas kernel, ops/pallas)
  - pre-norm blocks, gelu MLP
  - every Linear weight carries a PartitionSpec hint so pjit shards
    Megatron-style over the 'mp' axis with zero code changes
    (attention QKV column-parallel, attn-out row-parallel; MLP in
    column-parallel, MLP out row-parallel; embeddings vocab-parallel)
  - layers are homogeneous -> pipeline engine can split evenly over 'pp'
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed import mesh as mesh_mod
from ..nn.transformer import scaled_dot_product_attention


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_seq_len=1024,
                 dropout=0.1, attn_dropout=0.1, initializer_range=0.02,
                 use_recompute=False, sequence_parallel=False,
                 moe_experts=0, moe_k=2, moe_capacity_factor=1.25,
                 fused_head_loss=None, attn_layout=None,
                 attn_window=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute
        # long-context sequence parallelism over the 'sp' mesh axis — new
        # capability vs the reference. False | True/"ring" (ring attention,
        # distributed/ring_attention.py) | "ulysses" (all-to-all head
        # redistribution, distributed/ulysses.py)
        self.sequence_parallel = sequence_parallel
        # MoE FFN: >0 replaces every block's MLP with an expert-parallel
        # MoELayer over the 'ep' mesh axis (incubate/moe.py)
        self.moe_experts = int(moe_experts)
        self.moe_k = moe_k
        self.moe_capacity_factor = moe_capacity_factor
        # vocab-chunked fused LM-head + CE (ops/chunked_ce.py): the [B,S,V]
        # logits never hit HBM in training (XLA DCEs the unfused head
        # matmul when only the loss is consumed). None = AUTO by logits
        # size at forward time: chunking pays one extra matmul pass plus
        # per-chunk [N,C] intermediates, which only wins once the dense
        # logits are too big to ride HBM comfortably (measured on-chip:
        # gpt2s b=8 s=1024 v=32k runs ~20ms/step FASTER dense)
        self.fused_head_loss = (None if fused_head_loss is None
                                else bool(fused_head_loss))
        # attention kernel layout: "bshd" (default — kernel reads the
        # [B,S,H,D] qkv projection natively via packed 128-lane head
        # groups, no layout transposes) or "bhsd". Measured on-chip
        # (v5e, 2026-08-01): gpt2s b=8 64.2 vs 66.4 ms/step, BERT-base
        # b=16 63.9 vs 67.7 — bshd wins both, so it is the default; env
        # PT_ATTN_LAYOUT lets the bench A/B it without code changes.
        import os as _os
        self.attn_layout = (attn_layout
                            or _os.environ.get("PT_ATTN_LAYOUT", "bshd"))
        # causal sliding-window attention (last W keys per query); the
        # flash kernels skip KV blocks outside the band — O(S*W) attention
        # for long context. None = full causal.
        self.attn_window = None if attn_window is None else int(attn_window)


def gpt2_small(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt2_medium(**kw):
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=nn.ParamAttr(
            initializer=init))
        self.out_proj = nn.Linear(h, h, weight_attr=nn.ParamAttr(
            initializer=I.Normal(0.0, cfg.initializer_range
                                 / math.sqrt(2 * cfg.num_layers))))
        self.attn_dropout_p = cfg.attn_dropout
        self.attn_layout = getattr(cfg, "attn_layout", "bshd")
        self.attn_window = getattr(cfg, "attn_window", None)
        self.sequence_parallel = cfg.sequence_parallel
        if self.attn_window is not None and cfg.sequence_parallel:
            raise ValueError(
                "attn_window with sequence_parallel is not implemented: "
                "the ring/ulysses paths compute full causal attention "
                "(a silent full-attention fallback would train a "
                "different model than configured)")
        if cfg.sequence_parallel and cfg.attn_dropout:
            import warnings
            warnings.warn(
                "sequence_parallel ring attention does not apply "
                "attention-prob dropout; attn_dropout is ignored "
                "(residual dropout still applies)")
        self.resid_dropout = nn.Dropout(cfg.dropout)
        # Megatron shardings: QKV column-parallel, out row-parallel
        self.qkv_proj.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.qkv_proj.bias.sharding = P(mesh_mod.MP_AXIS)
        self.out_proj.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)                       # [B,S,3H]
        if self.attn_layout == "bshd" and not self.sequence_parallel \
                and not (self.attn_dropout_p and self.training):
            # BSHD fast path: the kernel reads [B,S,H,D] natively, so the
            # only layout op is the free reshape off the qkv matmul —
            # kills the bf16 [B,H,S,D] transposes (PERF.md hotspot #1).
            # q/k/v split indexes the UNSHARDED size-3 axis: the head axis
            # carries the Megatron mp sharding and slicing across it would
            # make GSPMD insert collectives inside per-stage control flow
            qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
            q = qkv[:, :, 0]
            k = qkv[:, :, 1]
            v = qkv[:, :, 2]
            from ..ops.pallas import flash_attention as _fa
            out = _fa(q, k, v, causal=True, layout="bshd",
                      window=self.attn_window)
            out = out.reshape([b, s, h])
            return self.resid_dropout(self.out_proj(out))
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        qkv = qkv.transpose([2, 0, 3, 1, 4])          # [3,B,Hd,S,D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        if self.sequence_parallel:
            # sequence parallelism over 'sp'; attention-prob dropout is
            # skipped on this path (scores are never materialised globally).
            # "ring" (default) streams K/V around the ICI ring; "ulysses"
            # all-to-alls to head-sharded full-sequence attention.
            if self.sequence_parallel == "ulysses":
                from ..distributed.ulysses import ulysses_flash_attention
                out = ulysses_flash_attention(q, k, v, causal=True)
            elif self.sequence_parallel in (True, "ring"):
                from ..distributed.ring_attention import ring_flash_attention
                out = ring_flash_attention(q, k, v, causal=True)
            else:
                raise ValueError(
                    f"unknown sequence_parallel={self.sequence_parallel!r}; "
                    "expected False, True/'ring', or 'ulysses'")
        else:
            if self.attn_window is not None:
                from ..ops.pallas import flash_attention as _fa
                out = _fa(q, k, v, causal=True, window=self.attn_window,
                          dropout_p=(self.attn_dropout_p
                                     if self.training else 0.0))
            else:
                out = scaled_dot_product_attention(
                    q, k, v, causal=True, dropout_p=self.attn_dropout_p,
                    training=self.training)
        out = out.transpose([0, 2, 1, 3]).reshape([b, s, h])
        return self.resid_dropout(self.out_proj(out))

    # -------------------------------------------------- incremental decode
    def init_cache(self, batch, max_len, dtype=jnp.float32):
        """KV cache [B, heads, L, head_dim] x2 (ref paddlenlp gen cache /
        fused multi-transformer CacheKV)."""
        shape = (batch, self.num_heads, max_len, self.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def init_paged_cache(self, num_blocks, block_size, dtype=jnp.float32):
        """Block-pool KV cache [num_blocks, heads, block_size, head_dim]
        x2 — requests claim BLOCKS (named by a host-managed table), not
        dense rows; see serving/paged."""
        shape = (num_blocks, self.num_heads, block_size, self.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def decode(self, x_t, cache, pos, block_tables=None):
        """One-token step: write K/V at `pos`, attend q over cache[:pos].
        x_t: [B, 1, H] Tensor; pos: traced int — a scalar (lockstep
        batch) or a [B] vector (slot-wise serving decode: per-row cache
        scatter + per-row mask, same shapes, one program). With
        block_tables [B, nblk], `cache` is the block POOL: K/V scatter
        through the table and attention reads the gathered per-row
        view — same fixed shapes, one program for every allocation."""
        b = x_t.shape[0]
        qkv = self.qkv_proj(x_t)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        a = a.reshape(b, 1, 3, self.num_heads, self.head_dim)
        a = jnp.transpose(a, (2, 0, 3, 1, 4))           # [3, B, nh, 1, D]
        q, k_t, v_t = a[0], a[1], a[2]
        ck, cv = cache
        from ..nn.paged_attention import paged_decode_attention
        from ..nn.transformer import (cached_decode_attention,
                                      scatter_block_kv_at, scatter_kv_at)
        if block_tables is not None:
            # fused path: attention reads K/V straight out of the pool
            # through the table (dispatch: reference | lax | pallas) —
            # the [B, Hkv, nblk*BS, D] gathered view never exists
            ck = scatter_block_kv_at(ck, k_t, block_tables, pos)
            cv = scatter_block_kv_at(cv, v_t, block_tables, pos)
            out = paged_decode_attention(q, ck, cv, block_tables, pos,
                                         1.0 / math.sqrt(self.head_dim),
                                         window=self.attn_window)
        else:
            if jnp.ndim(pos):
                ck = scatter_kv_at(ck, k_t, pos)
                cv = scatter_kv_at(cv, v_t, pos)
            else:
                ck = jax.lax.dynamic_update_slice_in_dim(
                    ck, k_t.astype(ck.dtype), pos, axis=2)
                cv = jax.lax.dynamic_update_slice_in_dim(
                    cv, v_t.astype(cv.dtype), pos, axis=2)
            out = cached_decode_attention(q, ck, cv, pos,
                                          1.0 / math.sqrt(self.head_dim),
                                          window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, -1)
        out = self.out_proj(Tensor(out.astype(x_t._data.dtype)))
        return out, (ck, cv)

    def prefill_chunk(self, x, cache, block_tables, chunk_start, valid_len):
        """One prompt CHUNK [1, C, H] against the block pool: scatter the
        chunk's K/V through the table at absolute positions chunk_start +
        arange(C) (the padded tail past valid_len goes to scratch), then
        attend the C queries over the gathered view — previous chunks'
        cached positions plus this chunk's own causal prefix
        (chunk_attention masks ks <= chunk_start + i)."""
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        a = a.reshape(b, s, 3, self.num_heads, self.head_dim)
        a = jnp.transpose(a, (2, 0, 3, 1, 4))           # [3, B, nh, C, D]
        q, k, v = a[0], a[1], a[2]
        ck, cv = cache
        from ..nn.paged_attention import paged_chunk_attention
        from ..nn.transformer import scatter_block_kv_chunk
        positions = chunk_start + jnp.arange(s)
        ck = scatter_block_kv_chunk(ck, k, block_tables, positions,
                                    valid_len)
        cv = scatter_block_kv_chunk(cv, v, block_tables, positions,
                                    valid_len)
        out = paged_chunk_attention(q, ck, cv, block_tables,
                                    chunk_start,
                                    1.0 / math.sqrt(self.head_dim),
                                    window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, h)
        return self.out_proj(Tensor(out.astype(x._data.dtype))), (ck, cv)

    def decode_chunk(self, x, cache, block_tables, start, valid_len):
        """Speculative verify step: C tokens for EVERY lane at once
        (x: [S, C, H]; start/valid_len: [S]) — the batched, per-lane-
        offset sibling of prefill_chunk. K/V scatter through every
        lane's table in one op (writes at i >= valid_len[s] go to
        scratch: horizon / spec_len clamp) and chunk_attention's
        vector start gives each query row its own causal frontier."""
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        a = a.reshape(b, s, 3, self.num_heads, self.head_dim)
        a = jnp.transpose(a, (2, 0, 3, 1, 4))           # [3, S, nh, C, D]
        q, k, v = a[0], a[1], a[2]
        ck, cv = cache
        from ..nn.paged_attention import paged_chunk_attention
        from ..nn.transformer import scatter_block_kv_chunk_batched
        ck = scatter_block_kv_chunk_batched(ck, k, block_tables, start,
                                            valid_len)
        cv = scatter_block_kv_chunk_batched(cv, v, block_tables, start,
                                            valid_len)
        out = paged_chunk_attention(q, ck, cv, block_tables, start,
                                    1.0 / math.sqrt(self.head_dim),
                                    window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, h)
        return self.out_proj(Tensor(out.astype(x._data.dtype))), (ck, cv)

    def prefill(self, x, cache):
        """Prompt-phase step: the forward attention math over x [B, P, H]
        that also writes the prompt's K/V into cache[:, :, :P] so decode
        continues at pos=P (cells past the true prompt length are rewritten
        by the decode frontier before the ks<=pos mask ever exposes them)."""
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        a = qkv._data if isinstance(qkv, Tensor) else qkv
        a = a.reshape(b, s, 3, self.num_heads, self.head_dim)
        a = jnp.transpose(a, (2, 0, 3, 1, 4))           # [3, B, nh, S, D]
        q, k, v = a[0], a[1], a[2]
        ck, cv = cache
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                          (0, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                          (0, 0, 0, 0))
        from ..ops.pallas.flash_attention import _flash_array
        out = _flash_array(q, k, v, causal=True, window=self.attn_window)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, h)
        return self.out_proj(Tensor(out.astype(x._data.dtype))), (ck, cv)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.fc_in = nn.Linear(cfg.hidden_size, cfg.ffn_hidden_size,
                               weight_attr=nn.ParamAttr(initializer=init))
        self.fc_out = nn.Linear(cfg.ffn_hidden_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(
                                    initializer=I.Normal(
                                        0.0, cfg.initializer_range
                                        / math.sqrt(2 * cfg.num_layers))))
        self.dropout = nn.Dropout(cfg.dropout)
        self.fc_in.weight.sharding = P(None, mesh_mod.MP_AXIS)
        self.fc_in.bias.sharding = P(mesh_mod.MP_AXIS)
        self.fc_out.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x),
                                               approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size)
        if cfg.moe_experts:
            from ..incubate.moe import MoELayer
            self.mlp = MoELayer(cfg.hidden_size, cfg.ffn_hidden_size,
                                cfg.moe_experts, k=cfg.moe_k,
                                capacity_factor=cfg.moe_capacity_factor,
                                initializer_range=cfg.initializer_range)
        else:
            self.mlp = GPTMLP(cfg)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        m = self.mlp(self.ln_2(x))
        if isinstance(m, tuple):         # MoE FFN: (out, aux_loss)
            x = x + m[0]
            return x, m[1]
        return x + m

    def decode(self, x, cache, pos, block_tables=None):
        a, cache = self.attn.decode(self.ln_1(x), cache, pos,
                                    block_tables=block_tables)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, cache

    def prefill(self, x, cache):
        a, cache = self.attn.prefill(self.ln_1(x), cache)
        x = x + a
        m = self.mlp(self.ln_2(x))
        if isinstance(m, tuple):         # MoE FFN: (out, aux_loss) — aux
            m = m[0]                     # is a training-only signal
        return x + m, cache

    def prefill_chunk(self, x, cache, block_tables, chunk_start, valid_len):
        a, cache = self.attn.prefill_chunk(self.ln_1(x), cache,
                                           block_tables, chunk_start,
                                           valid_len)
        x = x + a
        m = self.mlp(self.ln_2(x))
        if isinstance(m, tuple):         # MoE FFN: aux is training-only
            m = m[0]
        return x + m, cache

    def decode_chunk(self, x, cache, block_tables, start, valid_len):
        a, cache = self.attn.decode_chunk(self.ln_1(x), cache,
                                          block_tables, start, valid_len)
        x = x + a
        m = self.mlp(self.ln_2(x))
        if isinstance(m, tuple):         # MoE FFN: aux is training-only
            m = m[0]
        return x + m, cache


class GPTEmbeddings(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.position_embeddings = nn.Embedding(
            cfg.max_seq_len, cfg.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.dropout = nn.Dropout(cfg.dropout)
        self.word_embeddings.weight.sharding = P(mesh_mod.MP_AXIS, None)

    def forward(self, input_ids, position_ids=None):
        import paddle_tpu as pt
        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = pt.arange(s, dtype="int32").unsqueeze(0)
        return self.dropout(self.word_embeddings(input_ids)
                            + self.position_embeddings(position_ids))


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        """Returns hidden states; with MoE blocks, (hidden, aux_total) —
        the aux loss flows through the data path (remat-safe: it is an
        output of each checkpointed block, so it is a valid outer-trace
        value; no global side-channel)."""
        x = self.embeddings(input_ids, position_ids)
        use_remat = self.cfg.use_recompute
        moe = bool(self.cfg.moe_experts)
        aux_total = None
        for blk in self.blocks:
            if use_remat:
                from ..incubate.recompute import recompute
                out = recompute(blk, x)
            else:
                out = blk(x)
            if moe:
                x, aux = out
                aux_total = aux if aux_total is None else aux_total + aux
            else:
                x = out
        h = self.ln_f(x)
        return (h, aux_total) if moe else h

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        if max_len > self.cfg.max_seq_len:
            raise ValueError(
                f"decode length {max_len} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}: the position-embedding gather at "
                "a traced pos would clamp silently")
        return [blk.attn.init_cache(batch, max_len, dtype)
                for blk in self.blocks]

    def init_paged_cache(self, num_blocks, block_size, max_len,
                         dtype=jnp.float32):
        """Per-layer block pools [num_blocks, heads, block_size, hd] x2.
        max_len is the per-request horizon (nblk * block_size) — checked
        against the position-embedding table here because inside the
        decode wave `pos` is traced and the gather would clamp
        silently."""
        if max_len > self.cfg.max_seq_len:
            raise ValueError(
                f"decode length {max_len} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}: the position-embedding gather "
                "at a traced pos would clamp silently")
        return [blk.attn.init_paged_cache(num_blocks, block_size, dtype)
                for blk in self.blocks]

    def decode_step(self, tok, caches, pos, block_tables=None):
        """tok: [B, 1] ids; pos: traced position — a scalar, or a [B]
        vector for slot-wise serving decode. With block_tables [B, nblk]
        the caches are block POOLS (paged serving engine). Returns
        (h, caches)."""
        pos = pos._data if isinstance(pos, Tensor) else pos
        if jnp.ndim(pos):
            pos_ids = jnp.asarray(pos, jnp.int32)[:, None]
        else:
            pos_ids = jnp.full(
                (tok.shape[0] if hasattr(tok, "shape") else 1, 1),
                0, jnp.int32) + pos
        x = self.embeddings(tok, Tensor(pos_ids))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.decode(x, cache, pos,
                                  block_tables=block_tables)
            new_caches.append(cache)
        return self.ln_f(x), new_caches

    def prefill_chunk(self, tok_chunk, caches, block_tables, chunk_start,
                      valid_len):
        """One prompt chunk [1, C] ids at absolute positions chunk_start
        + arange(C) against the block pools (chunked prefill: long
        prompts run C tokens at a time between decode waves, writing K/V
        through the slot's block table). Returns (h, caches)."""
        c = tok_chunk.shape[1]
        pos_ids = (chunk_start + jnp.arange(c, dtype=jnp.int32))[None, :]
        x = self.embeddings(tok_chunk, Tensor(pos_ids))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.prefill_chunk(x, cache, block_tables,
                                         chunk_start, valid_len)
            new_caches.append(cache)
        return self.ln_f(x), new_caches

    def decode_chunk(self, tok_chunk, caches, block_tables, start,
                     valid_len):
        """Speculative verify: C tokens per lane ([S, C] ids) at
        per-lane absolute positions start[s] + i against the block
        pools. Position-embedding rows are gathered at the per-lane
        position matrix (out-of-table pad positions clamp harmlessly —
        their K/V is scratch-redirected and their logits masked)."""
        block_tables = (block_tables._data
                        if isinstance(block_tables, Tensor)
                        else block_tables)
        start = start._data if isinstance(start, Tensor) else start
        valid_len = (valid_len._data if isinstance(valid_len, Tensor)
                     else valid_len)
        c = tok_chunk.shape[1]
        pos_ids = jnp.minimum(
            start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :],
            self.cfg.max_seq_len - 1)
        x = self.embeddings(tok_chunk, Tensor(pos_ids))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.decode_chunk(x, cache, block_tables, start,
                                        valid_len)
            new_caches.append(cache)
        return self.ln_f(x), new_caches

    def prefill(self, input_ids, max_len, dtype=jnp.float32):
        """Prompt-phase forward over [B, P] ids that also populates fresh
        [B, heads, max_len, head_dim] KV caches for positions [0, P).
        Returns (hidden, caches) — decode continues at pos=P."""
        x = self.embeddings(input_ids)
        caches = self.init_cache(input_ids.shape[0], max_len, dtype)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.prefill(x, cache)
            new_caches.append(cache)
        return self.ln_f(x), new_caches


class GPTForPretraining(nn.Layer):
    """LM head tied to word embeddings (ref weight-tying convention)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        self.cfg = cfg

    def forward(self, input_ids, position_ids=None):
        out = self.gpt(input_ids, position_ids)
        hidden, aux = out if isinstance(out, tuple) else (out, None)
        w = self.gpt.embeddings.word_embeddings.weight
        from ..ops.math import matmul
        logits = matmul(hidden, w, transpose_y=True)
        if aux is not None:
            # ride the exact Tensor handed to the loss fn — per-call, no
            # global state, safe across interleaved models/forwards
            logits._moe_aux_loss = aux
        if _use_fused_head(self.cfg, logits.shape):
            # hand the loss fn the pre-head pieces: gpt_pretrain_loss uses
            # the vocab-chunked fused CE and never touches `logits`, so
            # under jit the dense head matmul above is dead code (users who
            # consume logits directly still get them). The ARRAY snapshot
            # of w matters: functional_call restores Parameter._data on
            # exit, and the loss fn runs after — holding only the Tensor
            # would silently swap the traced weight for a constant and
            # drop the head's gradient into the tied embedding.
            logits._fused_head = (hidden, w, w._data)
        return logits

    def loss(self, logits, labels):
        return gpt_pretrain_loss(logits, labels)

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        return self.gpt.init_cache(batch, max_len, dtype)

    def init_paged_cache(self, num_blocks, block_size, max_len,
                         dtype=jnp.float32):
        return self.gpt.init_paged_cache(num_blocks, block_size, max_len,
                                         dtype)

    def decode_step(self, tok, caches, pos, block_tables=None):
        h, caches = self.gpt.decode_step(tok, caches, pos,
                                         block_tables=block_tables)
        w = self.gpt.embeddings.word_embeddings.weight
        from ..ops.math import matmul
        return matmul(h, w, transpose_y=True), caches

    def decode_chunk(self, tok_chunk, caches, block_tables, start,
                     valid_len):
        """Speculative verify: logits for ALL C positions of every lane
        ([S, C, V] — the k+1-proportional head cost the verify program
        pays on purpose: one batched forward scores the whole drafted
        span)."""
        h, caches = self.gpt.decode_chunk(tok_chunk, caches,
                                          block_tables, start, valid_len)
        w = self.gpt.embeddings.word_embeddings.weight
        from ..ops.math import matmul
        return matmul(h, w, transpose_y=True), caches

    def prefill_chunk(self, tok_chunk, caches, block_tables, chunk_start,
                      valid_len, frontier=None):
        """One prompt chunk against the block pools. frontier (traced
        index WITHIN the chunk): logits for that one position only —
        [1, V] instead of [C, V], same trick as prefill; only the final
        chunk's frontier is consumed by the serving engine."""
        h, caches = self.gpt.prefill_chunk(tok_chunk, caches, block_tables,
                                           chunk_start, valid_len)
        if frontier is not None:
            hr = h._data if isinstance(h, Tensor) else h
            h = Tensor(jax.lax.dynamic_slice_in_dim(hr, frontier, 1,
                                                    axis=1))
        w = self.gpt.embeddings.word_embeddings.weight
        from ..ops.math import matmul
        return matmul(h, w, transpose_y=True), caches

    def prefill(self, input_ids, max_len, dtype=jnp.float32,
                frontier=None):
        """frontier (traced index): logits for that one prompt position
        only — keeps the serving prefill's vocab matmul [1, V] instead
        of [P, V] over the whole padded bucket."""
        h, caches = self.gpt.prefill(input_ids, max_len, dtype)
        if frontier is not None:
            hr = h._data if isinstance(h, Tensor) else h
            h = Tensor(jax.lax.dynamic_slice_in_dim(hr, frontier, 1,
                                                    axis=1))
        w = self.gpt.embeddings.word_embeddings.weight
        from ..ops.math import matmul
        return matmul(h, w, transpose_y=True), caches


# auto threshold for fused_head_loss=None: chunk once the f32 logits
# would exceed this (tests patch it to exercise both sides cheaply)
CHUNKED_CE_AUTO_BYTES = 2 << 30


def _use_fused_head(cfg, logits_shape):
    if cfg.fused_head_loss is not None:
        return cfg.fused_head_loss
    import numpy as _np
    if not all(isinstance(d, (int, _np.integer)) for d in logits_shape):
        # symbolic dims (shape-polymorphic jit.save export) have no
        # concrete size: keep the dense head — exported forwards serve
        # logits, they don't pair with the training-only fused loss
        return False
    b, s, v = (int(d) for d in logits_shape)
    return b * s * v * 4 > CHUNKED_CE_AUTO_BYTES


def gpt_pretrain_loss(logits, labels):
    """Next-token CE. Shift the LABELS (cheap int32 op) instead of slicing
    the logits: logits[:, :-1] yields a 1023-row tensor that breaks the
    TPU (8,128) tiling and costs a full relayout copy of the [B,S,V]
    logits (~512MB at the bench config, visible as reshape+fusion ops in
    the device trace); the last position is masked via ignore_index.

    When the model attached `_fused_head` (cfg.fused_head_loss), the loss
    is computed by the vocab-chunked fused head+CE (ops/chunked_ce.py)
    from the pre-head hidden states — the wide logits are never read, so
    XLA removes the dense head matmul entirely."""
    b, s, v = logits.shape
    from ..ops.manipulation import concat
    from ..ops.creation import full
    ign = full([b, 1], -1, dtype="int64")
    shifted = concat([labels[:, 1:].astype("int64"), ign], axis=1)
    fused = getattr(logits, "_fused_head", None)
    if fused is not None:
        import jax as _jax
        from ..ops.dispatch import apply
        from ..ops.chunked_ce import chunked_lm_loss
        hidden, w_t, w_arr = fused
        # traced: use the array snapshot — the Tensor's _data was restored
        # to the pre-trace constant when functional_call exited, and using
        # it would silently drop the head's grad into the tied embedding.
        # Eager: use the Tensor so the tape links w.grad.
        w_in = w_arr if isinstance(w_arr, _jax.core.Tracer) else w_t
        h2 = hidden.reshape([b * s, hidden.shape[-1]])
        lab = shifted.reshape([b * s])
        # small vocabs: chunk to the (128-aligned) vocab, not 4096 — padding
        # a 512-wide vocab to 4096 would 8x the head FLOPs
        chunk = min(4096, ((v + 127) // 128) * 128)

        def f(h_, w_, l_):
            return chunked_lm_loss(h_, w_, l_, -1, chunk)

        loss = apply(f, (h2, w_in, lab), name="chunked_lm_loss")
    else:
        loss = F.cross_entropy(logits.reshape([b * s, v]),
                               shifted.reshape([b * s]), ignore_index=-1)
    # MoE load-balance aux rides the logits Tensor (GPTForPretraining
    # attaches it); same-trace under TrainStep, concrete eagerly
    aux = getattr(logits, "_moe_aux_loss", None)
    if aux is not None:
        loss = loss + aux
    return loss


_GEN_CACHE_MAX = 8     # distinct (shape, knob) programs kept per model


def _gen_program_cache(model):
    """Per-model cache of traced generate programs: generate() used to
    build a fresh @jax.jit closure per call, so every call re-traced the
    whole model (seconds on a 1-core host) even when the XLA executable
    was disk-cached. The dict lives ON the model instance (the jitted
    closures capture the model, so a global weak map would never
    collect); model -> cache -> closure -> model is a plain cycle the
    gc reclaims when the model is dropped. Insertion-ordered, bounded:
    variable-shape serving loops evict oldest instead of accumulating
    one executable per (B, L, prompt_len) forever."""
    cache = getattr(model, "_pt_gen_programs", None)
    if cache is None:
        cache = {}
        # bypass Layer.__setattr__ (it interns sublayers/params)
        object.__setattr__(model, "_pt_gen_programs", cache)
    return cache


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
                 top_k=0, top_p=1.0, temperature=1.0, eos_token_id=None,
                 seed=None, use_cache=False):
    """Autoregressive decode for any causal LM exposing forward(ids) ->
    logits and (for use_cache=True) init_cache/decode_step — GPT and
    LLaMA both do (ref paddlenlp generation_utils.generate: greedy +
    top-k/top-p sampling).

    TPU-native: ONE jitted lax.fori_loop over a fixed [B, Lmax] buffer.
    use_cache=False recomputes the (causal) forward over the whole buffer
    per step and reads the frontier logits — exact, zero dynamic shapes,
    right for short decodes. use_cache=True runs the incremental KV-cache
    path (GPTModel.decode_step): O(T) attention per token against
    [B, heads, Lmax, head_dim] caches, the long-decode configuration;
    the prompt is consumed through the same single-token loop (prefill
    positions teacher-force from the buffer), so both paths are one
    compiled program.

    Returns ids [B, prompt_len + max_new_tokens] (prompt included), padded
    with eos after finish when eos_token_id is given.
    """
    import numpy as np
    from ..framework import state as _state
    from ..framework.tensor import Tensor as _T
    from ..nn.decode import top_k_top_p_filtering

    ids = input_ids._data if isinstance(input_ids, _T) else jnp.asarray(
        np.asarray(input_ids))
    ids = ids.astype(jnp.int32)
    B, prompt_len = ids.shape
    L = prompt_len + int(max_new_tokens)
    eos = -1 if eos_token_id is None else int(eos_token_id)

    was_training = model.training
    model.eval()            # generation is inference: dropout must be off
    params, buffers = model.functional_state()

    def logits_at(p, b, buf, t):
        out, _ = model.functional_call(p, b, _T(buf))
        lo = out._data if isinstance(out, _T) else out
        # frontier logits: position t-1 predicts token t
        return jax.lax.dynamic_index_in_dim(lo, t - 1, axis=1,
                                            keepdims=False)

    def make_step(p, b):
        def step(t, carry):
            buf, finished, key = carry
            lo = logits_at(p, b, buf, t).astype(jnp.float32)
            if temperature and temperature != 1.0:
                lo = lo / temperature
            if do_sample:
                lo = top_k_top_p_filtering(_T(lo), top_k=top_k,
                                           top_p=top_p)._data
                key, sub = jax.random.split(key)
                tok = jax.random.categorical(sub, lo,
                                             axis=-1).astype(jnp.int32)
            else:
                tok = jnp.argmax(lo, axis=-1).astype(jnp.int32)
            tok = jnp.where(finished, jnp.int32(max(eos, 0)), tok)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, tok[:, None], t, axis=1)
            if eos_token_id is not None:
                finished = finished | (tok == eos)
            return buf, finished, key
        return step

    buf0 = jnp.zeros((B, L), jnp.int32)
    buf0 = jax.lax.dynamic_update_slice_in_dim(buf0, ids, 0, axis=1)
    key0 = (jax.random.PRNGKey(seed) if seed is not None
            else _state.next_rng_key())

    spec = (B, L, prompt_len, bool(use_cache), bool(do_sample),
            int(top_k), float(top_p), float(temperature), eos)
    programs = _gen_program_cache(model)

    if not use_cache:
        if spec not in programs:
            @jax.jit
            def run(p, b, buf, key):
                # params enter as jit ARGUMENTS (not baked constants), so
                # repeated generate() calls after training reuse the program
                finished = jnp.zeros((B,), bool)
                buf, _, _ = jax.lax.fori_loop(prompt_len, L,
                                              make_step(p, b),
                                              (buf, finished, key))
                return buf
            programs[spec] = run
            while len(programs) > _GEN_CACHE_MAX:
                programs.pop(next(iter(programs)))

        try:
            return _T(programs[spec](params, buffers, buf0, key0))
        finally:
            if was_training:
                model.train()

    # ---------------- KV-cache path
    def make_cached_step(p, b):
        def step(t, carry):
            buf, caches, finished, key = carry
            tok_t = jax.lax.dynamic_slice_in_dim(buf, t, 1, axis=1)
            logits, caches = _functional_decode_step(model, p, b, tok_t,
                                                     caches, t)
            lo = logits[:, 0, :].astype(jnp.float32)
            if temperature and temperature != 1.0:
                lo = lo / temperature
            if do_sample:
                lo = top_k_top_p_filtering(_T(lo), top_k=top_k,
                                           top_p=top_p)._data
                key, sub = jax.random.split(key)
                tok = jax.random.categorical(sub, lo,
                                             axis=-1).astype(jnp.int32)
            else:
                tok = jnp.argmax(lo, axis=-1).astype(jnp.int32)
            # prefill positions teacher-force the known next token
            nxt = jnp.where(t + 1 < prompt_len, buf[:, (t + 1) % L], tok)
            nxt = jnp.where(finished, jnp.int32(max(eos, 0)), nxt)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None], jnp.minimum(t + 1, L - 1), axis=1)
            if eos_token_id is not None:
                finished = finished | ((t + 1 >= prompt_len) & (nxt == eos))
            return buf, caches, finished, key
        return step

    def _functional_decode_step(model, p, b, tok, caches, pos):
        out, _ = model.functional_call(
            p, b, _T(tok), caches, pos, method="decode_step")
        logits, new_caches = out
        return (logits._data if isinstance(logits, _T) else logits,
                new_caches)

    if spec not in programs:
        def _cache_dtype(p):
            # KV caches in the model's compute dtype: a bf16 model
            # decoding with f32 caches doubles the per-token HBM stream
            # (the decode einsum upcasts scores to f32 either way);
            # measured 2x decode tok/s on gpt2s b=8, which reads the
            # full [B,H,L,D] cache pair every token. Decided from the
            # TRACED params at trace time — jit retraces when param
            # dtypes change, so model.to(...) after a cached generate
            # cannot leave a stale dtype baked in — and by element-count
            # majority, so a model with only a bf16 embedding table
            # keeps f32 caches for its f32 attention compute.
            counts = {}
            for leaf in jax.tree_util.tree_leaves(p):
                dt = leaf.dtype
                if dt in (jnp.bfloat16, jnp.float16, jnp.float32):
                    counts[dt] = counts.get(dt, 0) + int(np.prod(leaf.shape))
            low = {d: c for d, c in counts.items() if d != jnp.float32}
            if low and sum(low.values()) > counts.get(jnp.float32, 0):
                return max(low, key=low.get)
            return jnp.float32

        @jax.jit
        def run_cached(p, b, buf, key):
            caches = model.init_cache(B, L, dtype=_cache_dtype(p))
            finished = jnp.zeros((B,), bool)
            buf, _, _, _ = jax.lax.fori_loop(
                0, L - 1, make_cached_step(p, b),
                (buf, caches, finished, key))
            return buf
        programs[spec] = run_cached
        while len(programs) > _GEN_CACHE_MAX:
            programs.pop(next(iter(programs)))

    try:
        return _T(programs[spec](params, buffers, buf0, key0))
    finally:
        if was_training:
            model.train()


gpt_generate = generate      # back-compat name
