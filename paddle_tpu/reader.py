"""paddle.reader / fluid.io reader decorators (ref python/paddle/reader/
decorator.py) — generator combinators for the legacy reader pipeline."""
import itertools
import random as _random

import numpy as np


def batch(reader, batch_size, drop_last=False):
    """ref paddle.batch: group a sample reader into lists of samples."""
    def batched():
        it = reader()
        while True:
            chunk = list(itertools.islice(it, batch_size))
            if not chunk:
                return
            if len(chunk) < batch_size and drop_last:
                return
            yield chunk
    return batched


def shuffle(reader, buf_size):
    """ref decorator.shuffle: buffered shuffling."""
    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf
    return shuffled


def buffered(reader, size):
    """ref decorator.buffered: thread-backed prefetch buffer. Reader
    exceptions propagate to the consumer (a swallowed error would look
    like a clean, shorter stream)."""
    import queue
    import threading

    def buffered_reader():
        q = queue.Queue(maxsize=size)
        end = object()
        err = []

        def fill():
            try:
                for s in reader():
                    q.put(s)
            except BaseException as e:
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                if err:
                    raise err[0]
                return
            yield s
    return buffered_reader


def compose(*readers, check_alignment=True):
    """ref decorator.compose: zip readers into joined samples."""
    def composed():
        its = [r() for r in readers]
        for samples in (zip(*its) if not check_alignment
                        else itertools.zip_longest(*its)):
            if check_alignment and any(s is None for s in samples):
                raise ValueError("compose: readers of different lengths")
            out = []
            for s in samples:
                out.extend(s if isinstance(s, tuple) else (s,))
            yield tuple(out)
    return composed


def chain(*readers):
    def chained():
        for r in readers:
            yield from r()
    return chained


def map_readers(func, *readers):
    def mapped():
        for samples in zip(*[r() for r in readers]):
            yield func(*samples)
    return mapped


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)
    return firstn_reader


def cache(reader):
    """Materialize once; a mid-iteration reader failure caches NOTHING
    (a partial prefix would silently truncate every later epoch)."""
    data = []

    def cached():
        if not data:
            data.extend(list(reader()))   # all-or-nothing
        return iter(data)
    return cached
