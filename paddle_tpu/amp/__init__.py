"""paddle_tpu.amp (ref python/paddle/amp: auto_cast + GradScaler;
fluid/contrib/mixed_precision for the static lists; kernels
operators/amp/check_finite_and_unscale_op.cc, update_loss_scaling_op.cc).

TPU-first: the low-precision dtype is bfloat16 (MXU native). bf16 shares
float32's exponent range, so dynamic loss scaling is unnecessary — GradScaler
keeps the reference API/state machine but defaults `use_loss_scaling=False`
when dtype is bf16 (enable=True + fp16 restores the full behavior).
"""
import contextlib

import numpy as np
import jax.numpy as jnp

from ..framework import state
from ..framework.tensor import Tensor
from ..ops.dispatch import AMP_WHITE_LIST, AMP_BLACK_LIST
from ..utils import telemetry, flight_recorder as _flight_recorder

_AMP_SKIPPED = telemetry.counter(
    "amp_skipped_steps_total",
    "Optimizer steps skipped because GradScaler saw inf/nan gradients")
_AMP_SCALE = telemetry.gauge(
    "amp_loss_scale", "Current GradScaler loss scale")


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """ref paddle/amp/auto_cast.py."""
    if not enable:
        yield
        return
    low = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    saved_w = saved_b = None
    if custom_white_list:
        saved_w = set(AMP_WHITE_LIST)
        AMP_WHITE_LIST.update(custom_white_list)
    if custom_black_list:
        saved_b = set(AMP_BLACK_LIST)
        AMP_BLACK_LIST.update(custom_black_list)
    try:
        with state.amp_guard_ctx({"level": level, "dtype": low}):
            yield
    finally:
        if saved_w is not None:
            AMP_WHITE_LIST.clear()
            AMP_WHITE_LIST.update(saved_w)
        if saved_b is not None:
            AMP_BLACK_LIST.clear()
            AMP_BLACK_LIST.update(saved_b)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """ref paddle/amp O2 decorate: cast model params to the low dtype.
    Optimizer moments stay fp32 (master weights) — see optimizer._init_state."""
    low = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    models_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for m in models_list:
            m.to(dtype=low)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """ref paddle/amp/grad_scaler.py:20 + fluid AmpScaler loss_scaler.py:27.
    Implements the check_finite_and_unscale + update_loss_scaling state
    machine (ref operators/amp/*) as pure jnp."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling and enable
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        if enable:
            _AMP_SCALE.set(self._scale)

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        from ..framework.selected_rows import SelectedRows
        found_inf = False
        inv = 1.0 / self._scale
        for p in optimizer._parameters:
            if p.grad is None:
                continue
            if isinstance(p.grad, SelectedRows):
                vals = p.grad.values
                if not bool(jnp.all(jnp.isfinite(vals))):
                    found_inf = True
                p.grad = SelectedRows(p.grad.rows, vals * inv,
                                      p.grad.height)
                continue
            g = p.grad._data
            finite = bool(jnp.all(jnp.isfinite(g)))
            if not finite:
                found_inf = True
            p.grad._data = g * inv
        self._found_inf = found_inf

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        else:
            # skipped optimizer step: counted on /metrics and journaled
            # through the current flight recorder (same path TrainStep's
            # sentinel uses), so loss-scale churn is visible post-mortem
            _AMP_SKIPPED.inc()
            recorder = _flight_recorder.get_recorder()
            if recorder is not None:
                recorder.nonfinite(source="amp_grad_scaler",
                                   loss_scale=float(self._scale))

    def update(self):
        if not self._dynamic:
            _AMP_SCALE.set(self._scale)
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        _AMP_SCALE.set(self._scale)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        """The loss-scaling state machine: current scale plus the
        good/bad step counters that drive the next grow/shrink decision
        — captured into full-state checkpoints (utils/resume.py) so a
        resumed run's scale trajectory continues instead of re-ramping
        from init_loss_scaling."""
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = float(sd["scale"])
        self._good_steps = int(sd["good_steps"])
        self._bad_steps = int(sd["bad_steps"])
        if self._enable:
            _AMP_SCALE.set(self._scale)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
