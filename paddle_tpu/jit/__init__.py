"""paddle_tpu.jit — dygraph -> compiled execution.

TPU-native answer to the reference's two compilation paths:
  - dy2static AST transpiler (ref fluid/dygraph/dygraph_to_static/
    program_translator.py:233): here `to_static` needs no AST surgery — the
    layer's python forward IS the trace function; jax.jit traces it through
    functional_call and XLA owns fusion/scheduling.
  - ParallelExecutor/CompiledProgram (ref compiler.py:164): `TrainStep`
    compiles forward+backward+optimizer into ONE donated XLA executable —
    params/opt-state update in place on HBM, host does a single dispatch per
    step (vs. the reference's per-op C++ loop, executor.cc:414).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import state
from ..framework.tensor import Tensor
from ..nn.layer import Layer


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _unwrap(v) for k, v in x.items()}
    return x


def _wrap(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _wrap(v) for k, v in x.items()}
    return x


def _split_static(args, kwargs):
    """Partition call arguments: array leaves become jit inputs, python
    scalars/strings stay COMPILE-TIME constants (the reference's
    dy2static contract — a python bool arg selects code paths and must
    not become a traced pred). Returns (dyn_leaves, hashable_meta)."""
    import numpy as np
    leaves, tree = jax.tree_util.tree_flatten((args, kwargs))
    dyn, static = [], []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, (jax.Array, np.ndarray)):
            dyn.append(leaf)
        else:
            static.append((i, leaf))
    return tuple(dyn), (tree, len(leaves), tuple(static))


_MISSING = object()


def _merge_static(dyn, meta):
    tree, n, static = meta
    leaves = [_MISSING] * n
    for i, v in static:
        leaves[i] = v
    it = iter(dyn)
    leaves = [next(it) if v is _MISSING else v for v in leaves]
    return jax.tree_util.tree_unflatten(tree, leaves)


class StaticFunction:
    """Wraps a Layer (or plain function) into a jit-compiled callable keeping
    the dygraph Tensor interface."""

    def __init__(self, fn_or_layer, input_spec=None):
        self._target = fn_or_layer
        self._is_layer = isinstance(fn_or_layer, Layer)
        self._compiled = None
        self._input_spec = input_spec

    def _build(self):
        from . import dy2static
        convert = ProgramTranslator.get_instance().enable_to_static
        if self._is_layer:
            layer = self._target
            if convert and "forward" not in layer.__dict__:
                # rewrite tensor-dependent `if`/`while` in forward so the
                # trace lowers them to lax.cond/while (dy2static analog);
                # patched on the instance so hooks/functional_call are kept
                import types as _types
                fwd = dy2static.convert_function(type(layer).forward)
                if fwd is not type(layer).forward:
                    layer.__dict__["forward"] = _types.MethodType(fwd, layer)

            def pure(params, buffers, key, dyn, meta):
                args, kwargs = _merge_static(dyn, meta)
                with state.functional_rng_ctx(key):
                    out, new_buf = layer.functional_call(
                        params, buffers, *_wrap(args), **_wrap(kwargs))
                return _unwrap(out), new_buf

            self._compiled = jax.jit(pure, static_argnums=(4,))
        else:
            fn = dy2static.convert_function(self._target) if convert \
                else self._target

            def pure(key, dyn, meta):
                args, kwargs = _merge_static(dyn, meta)
                with state.functional_mode_ctx():
                    with state.functional_rng_ctx(key):
                        out = fn(*_wrap(args), **_wrap(kwargs))
                return _unwrap(out)

            self._compiled = jax.jit(pure, static_argnums=(2,))

    def __call__(self, *args, **kwargs):
        if self._compiled is None:
            self._build()
        key = state.next_rng_key()
        dyn, meta = _split_static(_unwrap(args), _unwrap(kwargs))
        if self._is_layer:
            params, buffers = self._target.functional_state()
            out, new_buf = self._compiled(params, buffers, key, dyn, meta)
            # write back mutated buffers (BN running stats)
            named_b = dict(self._target.named_buffers())
            for n, arr in new_buf.items():
                named_b[n]._data = arr
            return _wrap(out)
        return _wrap(self._compiled(key, dyn, meta))

    # paddle surface
    @property
    def forward(self):
        return self.__call__


def to_static(layer_or_fn=None, input_spec=None, **kwargs):
    """paddle.jit.to_static analog (decorator or call)."""
    if layer_or_fn is None:
        return functools.partial(to_static, input_spec=input_spec, **kwargs)
    return StaticFunction(layer_or_fn, input_spec=input_spec)


class TrainStep:
    """Whole-train-step compiler: loss + grads + optimizer in one XLA program.

    Usage:
        step = TrainStep(model, loss_fn, opt)
        loss = step(x, y)          # one device dispatch
        step.sync()                # write state back into model/opt
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 return_outputs=False):
        from . import transforms as tfm
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.return_outputs = return_outputs
        params, buffers = model.functional_state()
        # copy: donated buffers are consumed by XLA, but the live Layer's
        # Parameters still reference the originals (callbacks/eager access
        # between steps must keep working — sync() writes back copies too)
        self.params = {n: jnp.copy(a) for n, a in params.items()}
        self.buffers = {n: jnp.copy(a) for n, a in buffers.items()}
        self.opt_state = optimizer.init_opt_state(params)
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()

        # strategy transforms recorded by the fleet meta-optimizer chain
        # (amp autocast, recompute, k-step gradient merge) — see
        # jit/transforms.py for the mapping
        self.transforms = tfm.resolve(optimizer)
        k_merge, merge_avg = tfm.merge_config(self.transforms)
        self.grad_acc = tfm.init_grad_acc(self.params, k_merge)
        update_fn = tfm.merged_update(apply_fn, k_merge, merge_avg)

        def _forward(p, bufs, key, inputs, labels):
            with state.functional_rng_ctx(key):
                out, new_buf = model.functional_call(
                    p, bufs, *_wrap(inputs))
                outs = out if isinstance(out, tuple) else (out,)
                loss_t = loss_fn(*outs, *_wrap(labels))
            return _unwrap(loss_t), (new_buf, _unwrap(out))

        _forward = tfm.wrap_forward(_forward, self.transforms)

        ret_outs = return_outputs

        def _step(params, buffers, opt_state, acc, key, lr, step_i,
                  inputs, labels):
            (loss, (new_buf, outs)), grads = jax.value_and_grad(
                lambda p: _forward(p, buffers, key, inputs, labels),
                has_aux=True)(params)
            new_params, new_opt, new_acc = update_fn(
                params, grads, opt_state, acc, lr, step_i)
            # outs leave the jitted program ONLY when asked for: a returned
            # value can't be dead-code-eliminated, and fused-loss models
            # (e.g. GPT chunked head+CE) rely on XLA dropping the unused
            # wide logits entirely
            if not ret_outs:
                outs = ()
            return loss, new_params, new_buf, new_opt, new_acc, outs

        donate_args = (0, 1, 2, 3) if donate else ()
        self._compiled = jax.jit(_step, donate_argnums=donate_args)

    def __call__(self, inputs, labels):
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        (loss, self.params, self.buffers, self.opt_state, self.grad_acc,
         outs) = self._compiled(
            self.params, self.buffers, self.opt_state, self.grad_acc,
            state.next_rng_key(),
            lr, jnp.asarray(self._step_i, jnp.int32),
            _unwrap(tuple(inputs)), _unwrap(tuple(labels)))
        if self.return_outputs:
            return Tensor(loss), _wrap(outs)
        return Tensor(loss)

    def eval_fn(self, fn=None):
        """Compile an eval forward over the live functional state."""
        model = self.model

        def _eval(params, buffers, inputs):
            was_training = model.training
            model.eval()
            try:
                out, _ = model.functional_call(params, buffers, *_wrap(inputs))
            finally:
                if was_training:
                    model.train()
            return _unwrap(out)

        compiled = jax.jit(_eval)

        def run(*inputs):
            return _wrap(compiled(self.params, self.buffers,
                                  _unwrap(tuple(inputs))))
        return run

    def sync(self):
        """Write functional state back into the Layer/Optimizer objects.
        Copies are handed out so subsequent donated steps can't invalidate
        the Layer's view."""
        named_p = dict(self.model.named_parameters())
        for n, arr in self.params.items():
            named_p[n]._data = jnp.copy(arr)
        named_b = dict(self.model.named_buffers())
        for n, arr in self.buffers.items():
            named_b[n]._data = jnp.copy(arr)
        opt = self.optimizer
        opt._global_step = self._step_i
        for n, st in self.opt_state.items():
            p = named_p[n]
            opt._accumulators[id(p)] = {k: jnp.copy(v) for k, v in st.items()}


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save analog (ref dygraph/jit.py:507): StableHLO export —
    see static/export.py for the on-disk format."""
    from ..static import export as _export
    if input_spec is None and isinstance(layer, StaticFunction):
        input_spec = layer._input_spec
        layer = layer._target
    return _export.save(layer, path, input_spec=input_spec, **configs)


def load(path, **configs):
    """paddle.jit.load analog (ref dygraph/jit.py:787) -> TranslatedLayer."""
    from ..static import export as _export
    return _export.load(path, **configs)


def not_to_static(fn):
    return fn


class ProgramTranslator:
    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self.enable_to_static = True

    def enable(self, enable_to_static):
        self.enable_to_static = enable_to_static
