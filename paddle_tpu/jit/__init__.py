"""paddle_tpu.jit — dygraph -> compiled execution.

TPU-native answer to the reference's two compilation paths:
  - dy2static AST transpiler (ref fluid/dygraph/dygraph_to_static/
    program_translator.py:233): here `to_static` needs no AST surgery — the
    layer's python forward IS the trace function; jax.jit traces it through
    functional_call and XLA owns fusion/scheduling.
  - ParallelExecutor/CompiledProgram (ref compiler.py:164): `TrainStep`
    compiles forward+backward+optimizer into ONE donated XLA executable —
    params/opt-state update in place on HBM, host does a single dispatch per
    step (vs. the reference's per-op C++ loop, executor.cc:414).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import state
from ..framework.tensor import Tensor
from ..nn.layer import Layer
from ..utils import chaos


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _unwrap(v) for k, v in x.items()}
    return x


def _wrap(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _wrap(v) for k, v in x.items()}
    return x


def _split_static(args, kwargs):
    """Partition call arguments: array leaves become jit inputs, python
    scalars/strings stay COMPILE-TIME constants (the reference's
    dy2static contract — a python bool arg selects code paths and must
    not become a traced pred). Returns (dyn_leaves, hashable_meta)."""
    import numpy as np
    leaves, tree = jax.tree_util.tree_flatten((args, kwargs))
    dyn, static = [], []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, (jax.Array, np.ndarray)):
            dyn.append(leaf)
        else:
            static.append((i, leaf))
    return tuple(dyn), (tree, len(leaves), tuple(static))


_MISSING = object()


def _merge_static(dyn, meta):
    tree, n, static = meta
    leaves = [_MISSING] * n
    for i, v in static:
        leaves[i] = v
    it = iter(dyn)
    leaves = [next(it) if v is _MISSING else v for v in leaves]
    return jax.tree_util.tree_unflatten(tree, leaves)


class StaticFunction:
    """Wraps a Layer (or plain function) into a jit-compiled callable keeping
    the dygraph Tensor interface."""

    def __init__(self, fn_or_layer, input_spec=None):
        import inspect
        self._method = None
        if inspect.ismethod(fn_or_layer) \
                and isinstance(fn_or_layer.__self__, Layer):
            # to_static(layer.forward): compile THROUGH the layer so its
            # Parameters join the autograd graph (a plain-function wrap
            # would see only the int input tensors and never train)
            self._target = fn_or_layer.__self__
            self._method = fn_or_layer.__func__
            self._is_layer = True
        else:
            self._target = fn_or_layer
            self._is_layer = isinstance(fn_or_layer, Layer)
        self._compiled = None
        self._input_spec = input_spec

    def _build(self):
        from . import dy2static
        convert = ProgramTranslator.get_instance().enable_to_static
        if self._is_layer:
            import types as _types
            layer = self._target
            base_fwd = self._method or type(layer).forward
            conv_fwd = dy2static.convert_function(base_fwd) if convert \
                else base_fwd
            conv_method = _types.MethodType(conv_fwd, layer)

            def pure(params, buffers, key, dyn, meta):
                args, kwargs = _merge_static(dyn, meta)
                # swap the converted forward in for the trace: the user
                # may have assigned THIS StaticFunction to layer.forward
                # (paddle idiom `model.forward = to_static(model.forward)`)
                # and dispatching through it again would recurse
                prev = layer.__dict__.get("forward", _MISSING)
                layer.__dict__["forward"] = conv_method
                try:
                    with state.functional_rng_ctx(key):
                        out, new_buf = layer.functional_call(
                            params, buffers, *_wrap(args), **_wrap(kwargs))
                finally:
                    if prev is _MISSING:
                        layer.__dict__.pop("forward", None)
                    else:
                        layer.__dict__["forward"] = prev
                return _unwrap(out), new_buf

            self._pure = pure
            self._compiled = jax.jit(pure, static_argnums=(4,))
        else:
            fn = dy2static.convert_function(self._target) if convert \
                else self._target

            def pure(key, dyn, meta):
                args, kwargs = _merge_static(dyn, meta)
                with state.functional_mode_ctx():
                    with state.functional_rng_ctx(key):
                        out = fn(*_wrap(args), **_wrap(kwargs))
                return _unwrap(out)

            self._pure = pure
            self._compiled = jax.jit(pure, static_argnums=(2,))

        # recompute-backward for eager training THROUGH the compiled
        # forward (the reference's ProgramTranslator captures backward in
        # the program, program_translator.py:233; here the whole jitted
        # forward is ONE tape op whose vjp re-derives the backward inside
        # jit — rematerialized, so nothing outlives the XLA program).
        # float_idx (static) selects the differentiable output slots.
        def bwd(p_leaves, dyn, buffers, key, cots, meta, names, float_idx):
            def f(*prims):
                p = dict(zip(names, prims[:len(names)]))
                d = tuple(prims[len(names):])
                if self._is_layer:
                    out, _ = self._pure(p, buffers, key, d, meta)
                else:
                    out = self._pure(key, d, meta)
                leaves = jax.tree_util.tree_flatten(out)[0]
                return tuple(leaves[i] for i in float_idx)

            _, vjp = jax.vjp(f, *(tuple(p_leaves) + tuple(dyn)))
            return vjp(tuple(cots))

        self._bwd = jax.jit(bwd, static_argnums=(5, 6, 7))

    def __call__(self, *args, **kwargs):
        if self._compiled is None:
            self._build()
        key = state.next_rng_key()
        dyn, meta = _split_static(_unwrap(args), _unwrap(kwargs))
        if self._is_layer:
            params, buffers = self._target.functional_state()
            out, new_buf = self._compiled(params, buffers, key, dyn, meta)
            # write back mutated buffers (BN running stats)
            named_b = dict(self._target.named_buffers())
            for n, arr in new_buf.items():
                named_b[n]._data = arr
        else:
            params, buffers = {}, {}
            out = self._compiled(key, dyn, meta)
        wrapped = _wrap(out)
        if state.is_functional_mode() or not state.is_grad_enabled():
            return wrapped
        self._record_grad(wrapped, args, kwargs, params, buffers, key,
                          dyn, meta)
        return wrapped

    def _record_grad(self, wrapped, args, kwargs, params, buffers, key,
                     dyn, meta):
        """Attach ONE GradNode covering the whole compiled forward, so
        eager `loss.backward()` flows into the layer's Parameters and
        any differentiable input Tensors. Double-grad (create_graph)
        through a to_static function is not supported (fn=None)."""
        from ..framework.tape import GradNode

        # original Tensor objects aligned with the dyn leaves: _unwrap is
        # structure-preserving, so wrapped and unwrapped trees flatten to
        # the same leaf positions
        w_leaves = jax.tree_util.tree_flatten((args, kwargs))[0]
        u_leaves = jax.tree_util.tree_flatten(
            (_unwrap(args), _unwrap(kwargs)))[0]
        dyn_tensors = [w if isinstance(w, Tensor) else None
                       for w, u in zip(w_leaves, u_leaves)
                       if isinstance(u, (jax.Array, np.ndarray))]

        names = tuple(params)
        named_p = dict(self._target.named_parameters()) \
            if self._is_layer else {}
        p_tensors = [named_p.get(n) for n in names]
        inputs = p_tensors + dyn_tensors
        if not any(t is not None and not t.stop_gradient for t in inputs):
            return

        # ONE flatten defines the slot numbering: every leaf is a slot;
        # only float Tensor slots are differentiable (float_idx), and the
        # same indexing selects the cotangents the tape hands back
        leaves_w = jax.tree_util.tree_flatten(
            wrapped, is_leaf=lambda x: isinstance(x, Tensor))[0]
        arrs = [w._data if isinstance(w, Tensor) else w for w in leaves_w]
        float_idx = tuple(
            i for i, (w, a) in enumerate(zip(leaves_w, arrs))
            if isinstance(w, Tensor)
            and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating))
        if not float_idx:
            return
        p_leaves = tuple(params[n] for n in names)
        bwd = self._bwd

        def vjp_fn(cots):
            cots_t = cots if isinstance(cots, tuple) else (cots,)
            return bwd(p_leaves, dyn, buffers, key,
                       tuple(cots_t[i] for i in float_idx),
                       meta, names, float_idx)

        node = GradNode(
            vjp=vjp_fn,
            inputs=inputs,
            n_outputs=len(leaves_w),
            out_shapes=tuple(jnp.shape(a) for a in arrs),
            out_dtypes=tuple(jnp.asarray(a).dtype for a in arrs),
            name="to_static")
        for i in float_idx:
            leaves_w[i]._node = node
            leaves_w[i]._slot = i
            leaves_w[i].stop_gradient = False

    # paddle surface
    @property
    def forward(self):
        return self.__call__


def to_static(layer_or_fn=None, input_spec=None, **kwargs):
    """paddle.jit.to_static analog (decorator or call)."""
    if layer_or_fn is None:
        return functools.partial(to_static, input_spec=input_spec, **kwargs)
    return StaticFunction(layer_or_fn, input_spec=input_spec)


def grad_norm_sentinel(loss, grads):
    """(global_grad_norm, notfinite) fused into a compiled train step —
    ONE implementation for TrainStep and ShardedTrainStep: the
    (loss, grad_norm) pair is exactly what the kill/resume parity gate
    (scripts/chaos_train.py) compares across the two step flavours, so
    the reduction must never drift between them. A tiny fp32 reduction
    over the grads that XLA fuses into the backward — no extra host
    sync (the flag is only ever READ by an instrumented caller that is
    about to block anyway)."""
    gsq = sum((jnp.sum(jnp.square(g.astype(jnp.float32)))
               for g in jax.tree_util.tree_leaves(grads)),
              jnp.asarray(0.0, jnp.float32))
    notfinite = jnp.logical_not(
        jnp.all(jnp.isfinite(loss)) & jnp.isfinite(gsq))
    return jnp.sqrt(gsq), notfinite


class InstrumentedStepMixin:
    """Flight-recorder/watchdog instrumentation shared by the compiled
    train steps (`TrainStep` here, `distributed.sharded.ShardedTrainStep`).

    Hosts expectations: the step object carries `_compiled` (a jitted
    callable returning the canonical 8-tuple `(loss, params, buffers,
    opt_state, grad_acc, outs, grad_norm, notfinite)`), the state dicts
    those outputs rebind (`params`/`buffers`/`opt_state`/`grad_acc`),
    and `_step_i`. `_init_instrumentation()` must run in `__init__`."""

    def _init_instrumentation(self, label="train_step"):
        self._recorder = None
        self._label = label
        self._fail_fast = False
        self._cost_cache = {}
        self._pending_data_s = 0.0
        self._pending_batch = None
        self._watchdog = None
        self._last_grad_norm = None
        self._last_nonfinite = None

    # ------------------------------------------------------ flight recorder
    def attach_flight_recorder(self, recorder, label=None,
                               fail_fast=None, watchdog=None):
        """Instrument every subsequent step: journal `step` events with
        the data/host/device timing split, per-executable `compile`
        events with FLOPs/bytes from HLO cost analysis, MFU + non-finite
        telemetry. Adds ONE host sync per step (block_until_ready on the
        loss) — the same sync hapi's per-step float(loss) already pays.
        `fail_fast=True` (or recorder.fail_fast) raises NonFiniteError
        when loss/global-grad-norm go non-finite. `watchdog` (a started
        `utils.resume.TrainWatchdog`) is fed one `beat()` per completed
        step, so a step that never completes becomes a journaled `hang`
        event instead of a silent stall."""
        from ..utils import telemetry, flight_recorder as fr
        self._recorder = recorder
        if label is not None:
            self._label = label
        self._watchdog = watchdog
        self._fail_fast = recorder.fail_fast if fail_fast is None \
            else bool(fail_fast)
        # False on jax builds without jax.monitoring: compile detection
        # then falls back to _cache_size() deltas (same fallback
        # telemetry._InstrumentedJit uses)
        self._monitoring = telemetry.install_compile_tracking()
        self._peak_flops = fr.device_peak_flops()   # constant per process
        self._m_mfu = telemetry.gauge(
            "train_mfu", "Model-FLOPs utilization of the latest step")
        self._m_flops = telemetry.gauge(
            "train_step_flops",
            "FLOPs per compiled train step (HLO cost analysis)")
        self._m_bytes = telemetry.gauge(
            "train_step_bytes",
            "Bytes accessed per compiled train step (HLO cost analysis)")
        self._m_nonfinite = telemetry.counter(
            "train_nonfinite_total",
            "Train steps with non-finite loss or global grad norm")
        self._m_data = telemetry.histogram(
            "train_data_wait_seconds", "Input-pipeline wait per step")
        self._m_host = telemetry.histogram(
            "train_host_dispatch_seconds",
            "Host time dispatching the compiled step")
        self._m_dev = telemetry.histogram(
            "train_device_step_seconds",
            "Device execution time per step (block_until_ready)")
        return self

    def detach_flight_recorder(self):
        self._recorder = None
        self._watchdog = None

    def set_data_wait(self, seconds, batch=None):
        """Data-pipeline wait (and optionally the epoch-relative batch
        index) attributed to the NEXT step event (Model.fit times the
        DataLoader and reports both here — the journal's `batch` field
        is the same index the resume cursor records, so data-wait
        attribution and fast-forward bookkeeping agree)."""
        self._pending_data_s = float(seconds)
        self._pending_batch = None if batch is None else int(batch)

    def last_nonfinite(self):
        """Sentinel of the latest step (host sync on first read)."""
        return None if self._last_nonfinite is None \
            else bool(self._last_nonfinite)

    def last_grad_norm(self):
        return None if self._last_grad_norm is None \
            else float(self._last_grad_norm)

    def _safe_cache_size(self):
        try:
            return self._compiled._cache_size()
        except Exception:
            return 0

    def _signature(self, args):
        # dtype via attribute, NOT jnp.asarray: these are the raw batch
        # leaves and asarray would device-transfer numpy batches once
        # more per step just to read their dtype
        leaves = jax.tree_util.tree_flatten((args[7], args[8]))[0]
        return tuple(
            (jnp.shape(a), str(getattr(a, "dtype", type(a).__name__)))
            for a in leaves)

    def _instrumented_call(self, args):
        import time as _time
        from ..utils import telemetry, flight_recorder as fr
        rec = self._recorder
        sig = self._signature(args)
        if sig not in self._cost_cache:
            # once per executable, BEFORE the call donates the buffers:
            # lowering-level HLO cost analysis, no second backend compile
            self._cost_cache[sig] = fr.cost_analysis(self._compiled, *args)
        cost = self._cost_cache[sig] or {}
        before = telemetry.compile_count(self._label) if self._monitoring \
            else self._safe_cache_size()
        t0 = _time.perf_counter()
        with telemetry.track_compiles(self._label):
            (loss, self.params, self.buffers, self.opt_state, self.grad_acc,
             outs, self._last_grad_norm, self._last_nonfinite) = \
                self._compiled(*args)
        t1 = _time.perf_counter()
        loss.block_until_ready()
        t2 = _time.perf_counter()
        host_s, device_s = t1 - t0, t2 - t1
        if self._monitoring:
            compiled = telemetry.compile_count(self._label) - before
        else:
            compiled = max(0, self._safe_cache_size() - before)
            if compiled:
                telemetry.counter(
                    "xla_compiles_total", labelnames=("function",)
                ).labels(self._label).inc(compiled)
        flops = cost.get("flops")
        if compiled:
            rec.compile_event(self._label, count=compiled, compile_s=host_s,
                              flops=flops,
                              bytes_accessed=cost.get("bytes_accessed"))
        # gauges track the CURRENT executable's cost, not just freshly
        # compiled ones — a recorder attached after the compile (bench's
        # verification step) must still publish them
        if flops is not None:
            self._m_flops.set(flops)
        if cost.get("bytes_accessed") is not None:
            self._m_bytes.set(cost["bytes_accessed"])
        mfu = 0.0
        if flops:
            mfu = flops / (max(device_s, 1e-9) * self._peak_flops)
            self._m_mfu.set(mfu)
        data_s, self._pending_data_s = self._pending_data_s, 0.0
        batch_idx, self._pending_batch = self._pending_batch, None
        nonfinite = bool(self._last_nonfinite)
        grad_norm = float(self._last_grad_norm)
        extra = {} if batch_idx is None else {"batch": batch_idx}
        rec.step(step=self._step_i, data_s=data_s, host_s=host_s,
                 device_s=device_s, loss=float(loss), grad_norm=grad_norm,
                 mfu=mfu, nonfinite=nonfinite, **extra)
        if self._watchdog is not None:
            self._watchdog.beat(step_s=host_s + device_s, step=self._step_i)
        self._m_data.observe(data_s)
        self._m_host.observe(host_s)
        self._m_dev.observe(device_s)
        if nonfinite:
            self._m_nonfinite.inc()
            rec.nonfinite(step=self._step_i, loss=float(loss),
                          grad_norm=grad_norm, source=self._label)
            if self._fail_fast:
                rec.flush()
                raise fr.NonFiniteError(
                    f"non-finite loss/grad at step {self._step_i}: "
                    f"loss={float(loss)!r} grad_norm={grad_norm!r}")
        return loss, outs


class TrainStep(InstrumentedStepMixin):
    """Whole-train-step compiler: loss + grads + optimizer in one XLA program.

    Usage:
        step = TrainStep(model, loss_fn, opt)
        loss = step(x, y)          # one device dispatch
        step.sync()                # write state back into model/opt
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 return_outputs=False):
        from . import transforms as tfm
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.return_outputs = return_outputs
        params, buffers = model.functional_state()
        # copy: donated buffers are consumed by XLA, but the live Layer's
        # Parameters still reference the originals (callbacks/eager access
        # between steps must keep working — sync() writes back copies too)
        self.params = {n: jnp.copy(a) for n, a in params.items()}
        self.buffers = {n: jnp.copy(a) for n, a in buffers.items()}
        # parameters= threads the live Parameter objects through so an
        # optimizer carrying RESTORED accumulators (checkpoint resume,
        # or prior synced steps) seeds the functional state — a rebuilt
        # TrainStep must continue the trajectory, not zero the moments
        self.opt_state = optimizer.init_opt_state(
            params, parameters=dict(model.named_parameters()))
        self._step_i = optimizer._global_step
        apply_fn = optimizer.apply_gradients_fn()

        # strategy transforms recorded by the fleet meta-optimizer chain
        # (amp autocast, recompute, k-step gradient merge) — see
        # jit/transforms.py for the mapping
        self.transforms = tfm.resolve(optimizer)
        k_merge, merge_avg = tfm.merge_config(self.transforms)
        self.grad_acc = tfm.init_grad_acc(self.params, k_merge)
        update_fn = tfm.merged_update(apply_fn, k_merge, merge_avg)

        def _forward(p, bufs, key, inputs, labels):
            with state.functional_rng_ctx(key):
                # keep the param substitution alive THROUGH the loss call:
                # losses may read model parameters directly (CRF
                # transitions, tied heads) and must see the traced arrays,
                # not the pre-trace constants functional_call restores on
                # exit — otherwise those params silently train to nothing
                with model._use_state(p, bufs):
                    out, new_buf = model.functional_call(
                        p, bufs, *_wrap(inputs))
                    outs = out if isinstance(out, tuple) else (out,)
                    loss_t = loss_fn(*outs, *_wrap(labels))
            return _unwrap(loss_t), (new_buf, _unwrap(out))

        _forward = tfm.wrap_forward(_forward, self.transforms)

        ret_outs = return_outputs

        def _step(params, buffers, opt_state, acc, key, lr, step_i,
                  inputs, labels):
            (loss, (new_buf, outs)), grads = jax.value_and_grad(
                lambda p: _forward(p, buffers, key, inputs, labels),
                has_aux=True)(params)
            new_params, new_opt, new_acc = update_fn(
                params, grads, opt_state, acc, lr, step_i)
            grad_norm, notfinite = grad_norm_sentinel(loss, grads)
            # outs leave the jitted program ONLY when asked for: a returned
            # value can't be dead-code-eliminated, and fused-loss models
            # (e.g. GPT chunked head+CE) rely on XLA dropping the unused
            # wide logits entirely
            if not ret_outs:
                outs = ()
            return (loss, new_params, new_buf, new_opt, new_acc, outs,
                    grad_norm, notfinite)

        donate_args = (0, 1, 2, 3) if donate else ()
        # stashed for the program-level audit (tools/jxaudit): jax's
        # PjitFunction exposes no public donate introspection, so the
        # declaration of record rides on the TrainStep itself
        self._donate_argnums = donate_args
        self._compiled = jax.jit(_step, donate_argnums=donate_args)
        # flight-recorder instrumentation (attach_flight_recorder)
        self._init_instrumentation()

    def __call__(self, inputs, labels):
        if chaos.enabled():
            # the canonical "kill"/stall boundary for the exact-resume
            # parity harness: host-side, BEFORE the step counter, the
            # RNG draw, or the compiled dispatch — a raise here leaves
            # every piece of training state exactly at the last
            # completed step, like a SIGKILL between steps
            chaos.fire(chaos.TRAIN_STEP, step=self._step_i + 1)
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        args = (self.params, self.buffers, self.opt_state, self.grad_acc,
                state.next_rng_key(),
                lr, jnp.asarray(self._step_i, jnp.int32),
                _unwrap(tuple(inputs)), _unwrap(tuple(labels)))
        if self._recorder is not None:
            loss, outs = self._instrumented_call(args)
        else:
            (loss, self.params, self.buffers, self.opt_state, self.grad_acc,
             outs, self._last_grad_norm, self._last_nonfinite) = \
                self._compiled(*args)
        if self.return_outputs:
            return Tensor(loss), _wrap(outs)
        return Tensor(loss)

    def eval_fn(self, fn=None):
        """Compile an eval forward over the live functional state."""
        model = self.model

        def _eval(params, buffers, inputs):
            was_training = model.training
            model.eval()
            try:
                out, _ = model.functional_call(params, buffers, *_wrap(inputs))
            finally:
                if was_training:
                    model.train()
            return _unwrap(out)

        compiled = jax.jit(_eval)

        def run(*inputs):
            return _wrap(compiled(self.params, self.buffers,
                                  _unwrap(tuple(inputs))))
        return run

    def sync(self):
        """Write functional state back into the Layer/Optimizer objects.
        Copies are handed out so subsequent donated steps can't invalidate
        the Layer's view."""
        named_p = dict(self.model.named_parameters())
        for n, arr in self.params.items():
            named_p[n]._data = jnp.copy(arr)
        named_b = dict(self.model.named_buffers())
        for n, arr in self.buffers.items():
            named_b[n]._data = jnp.copy(arr)
        opt = self.optimizer
        opt._global_step = self._step_i
        for n, st in self.opt_state.items():
            p = named_p[n]
            opt._accumulators[id(p)] = {k: jnp.copy(v) for k, v in st.items()}


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save analog (ref dygraph/jit.py:507): StableHLO export —
    see static/export.py for the on-disk format."""
    from ..static import export as _export
    if input_spec is None and isinstance(layer, StaticFunction):
        input_spec = layer._input_spec
        layer = layer._target
    return _export.save(layer, path, input_spec=input_spec, **configs)


def load(path, **configs):
    """paddle.jit.load analog (ref dygraph/jit.py:787) -> TranslatedLayer."""
    from ..static import export as _export
    return _export.load(path, **configs)


def not_to_static(fn):
    return fn


class ProgramTranslator:
    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self.enable_to_static = True

    def enable(self, enable_to_static):
        self.enable_to_static = enable_to_static
