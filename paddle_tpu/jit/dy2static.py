"""dy2static — AST transpiler: tensor-dependent python control flow → lax.

TPU-native redesign of the reference dygraph_to_static stack
(ref python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py,
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
program_translator.py:233): the reference rewrites python AST into
ProgramDesc control-flow ops (conditional_block/while); here the same AST
surgery rewrites `if`/`while` statements into runtime helpers that pick
between plain python execution (concrete predicate — eager) and
`lax.cond`/`lax.while_loop` (traced predicate — inside jax.jit), so one
model source serves both programming models (SURVEY.md §7 P3).

Mechanics: each converted `if`/`while` becomes a cluster of nested
functions — branch bodies with `nonlocal` write-back, a getter and a
resetter for the captured variable tuple — mirroring the reference's
true_fn/false_fn + modified-name analysis (ifelse_transformer.py
NameVisitor), but without variable renaming because `nonlocal` gives
read/write access to the enclosing frame.

Deliberate limits (same spirit as the reference's unsupported lists):
- `if`/`while` bodies containing return/break/continue/yield are left as
  python (they still work eagerly; under tracing they raise jax's
  concretization error with a clear message);
- `for i in range(...)` lowers through the while machinery (tensor
  bounds become lax.while_loop; concrete ranges still unroll); other
  iterables (lists, enumerate, tensor iteration) stay python;
- variables flowing through converted control flow must be tensors/scalars
  when traced (strings/objects are closure-captured, branch-invariant).
"""
import ast
import functools
import inspect
import textwrap
import types
import warnings

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


# --------------------------------------------------------------------------- #
# runtime helpers (the `_jst` namespace emitted code calls into)              #
# --------------------------------------------------------------------------- #

class _Undef:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<UNDEF>"


UNDEF = _Undef()


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _any_traced(vals):
    return any(_is_traced(_unwrap(v)) for v in vals)


def _split_dynamic(vals):
    """Partition a variable tuple into (dynamic indices, static values).
    Dynamic = things lax can carry (tensors/arrays/python numbers)."""
    dyn_idx = []
    for i, v in enumerate(vals):
        u = _unwrap(v)
        if isinstance(u, (jax.Array, jax.core.Tracer, int, float, bool,
                          complex)) and not isinstance(v, _Undef):
            dyn_idx.append(i)
    return dyn_idx


def convert_ifelse(pred, true_fn, false_fn, get, reset):
    """Emitted for `if`: concrete pred runs one branch in place; traced pred
    lowers to lax.cond. Branch outputs are discovered during tracing: each
    branch closes over the enclosing frame (captured tracers become cond
    constants) and reports, per captured variable, whether it produced a
    dynamic value (carried through cond) or a static one (must agree across
    branches — same constraint the reference's ifelse_transformer imposes)."""
    p = _unwrap(pred)
    if not _is_traced(p):
        (true_fn if bool(p) else false_fn)()
        return get() if get is not None else ()
    if get is None:
        # no captured vars: still lower (branches may have jax side effects
        # like debug prints); outputs are empty
        jax.lax.cond(p, lambda _: (true_fn(), ())[1],
                     lambda _: (false_fn(), ())[1], None)
        return ()
    orig = get()
    specs = {}  # branch name -> list of ('dyn',) | ('static', value)

    def run(fn, tag):
        def branch(_):
            reset(orig)
            fn()
            out = get()
            spec, leaves = [], []
            for v in out:
                u = _unwrap(v)
                if isinstance(u, (jax.Array, jax.core.Tracer)) or \
                        isinstance(u, (int, float, bool)) and \
                        not isinstance(v, _Undef):
                    spec.append("dyn")
                    leaves.append(jnp.asarray(u))
                else:
                    spec.append(("static", v))
            specs[tag] = spec
            return tuple(leaves)
        return branch

    try:
        res = jax.lax.cond(p, run(true_fn, "true"), run(false_fn, "false"),
                           None)
    except (TypeError, ValueError) as e:
        # Diagnose: if the branches disagree on which vars are tensors,
        # lax.cond raises a generic pytree-structure error — both branch
        # specs were already collected during its tracing, so we can
        # replace it with an actionable message.
        both = specs.get("true"), specs.get("false")
        if all(s is not None for s in both) and any(
                (st == "dyn") != (sf == "dyn")
                for st, sf in zip(*both)):
            raise ValueError(
                "dy2static: a variable is a tensor in one branch of a "
                "traced `if` but not the other — assign it consistently "
                "in both branches") from e
        raise
    spec_t, spec_f = specs["true"], specs["false"]
    for st, sf in zip(spec_t, spec_f):
        if (st == "dyn") != (sf == "dyn"):
            raise ValueError(
                "dy2static: a variable is a tensor in one branch of a "
                "traced `if` but not the other — assign it consistently "
                "in both branches")
    final, j = [], 0
    for i, s in enumerate(spec_t):
        if s == "dyn":
            final.append(Tensor(res[j]) if isinstance(orig[i], Tensor)
                         or isinstance(orig[i], _Undef) else res[j])
            j += 1
        else:
            final.append(s[1])
    reset(tuple(final))
    return tuple(final)


def convert_while(cond_fn, body_fn, get, reset):
    """Emitted for `while`: concrete → python loop; traced condition or
    loop vars → lax.while_loop over the dynamic subset of captured vars
    (static vars are loop-invariant closure constants)."""
    first = _unwrap(cond_fn())
    orig = get() if get is not None else ()
    if not _is_traced(first) and not _any_traced(orig):
        while bool(_unwrap(cond_fn())):
            body_fn()
        return get() if get is not None else ()
    dyn_idx = _split_dynamic(orig)

    def put(carry):
        full = list(orig)
        for j, i in enumerate(dyn_idx):
            full[i] = Tensor(carry[j]) if isinstance(orig[i], Tensor) \
                else carry[j]
        reset(tuple(full))

    def c(carry):
        put(carry)
        return _unwrap(cond_fn())

    def b(carry):
        put(carry)
        body_fn()
        out = get()
        for i, v in enumerate(out):
            if i not in dyn_idx and _is_traced(_unwrap(v)) \
                    and not _is_traced(_unwrap(orig[i])):
                raise ValueError(
                    "dy2static: a variable becomes a tensor inside a traced "
                    "`while` body — initialize it as a tensor before the "
                    "loop (XLA loop carries need a fixed structure)")
        new = []
        for j, i in enumerate(dyn_idx):
            u = jnp.asarray(_unwrap(out[i]))
            new.append(u.astype(carry[j].dtype)
                       if u.dtype != carry[j].dtype else u)
        return tuple(new)

    carry0 = tuple(jnp.asarray(_unwrap(orig[i])) for i in dyn_idx)
    res = jax.lax.while_loop(c, b, carry0)
    final = list(orig)
    for j, i in enumerate(dyn_idx):
        final[i] = Tensor(res[j]) if isinstance(orig[i], Tensor) else res[j]
    reset(tuple(final))
    return tuple(final)


def check_step(step):
    """range() semantics: a CONCRETE zero step is an error (python raises
    ValueError); a traced step can't be checked at trace time."""
    u = _unwrap(step)
    if not _is_traced(u) and int(u) == 0:
        raise ValueError("range() arg 3 must not be zero")
    return step


def convert_logical_and(lhs_fn, rhs_fn):
    """ref logical_transformer.py convert_logical_and — preserves python
    short-circuit when concrete."""
    l = lhs_fn()
    lu = _unwrap(l)
    if not _is_traced(lu):
        if not bool(lu):
            return l
        return rhs_fn()
    return Tensor(jnp.logical_and(lu, _unwrap(rhs_fn())))


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    lu = _unwrap(l)
    if not _is_traced(lu):
        if bool(lu):
            return l
        return rhs_fn()
    return Tensor(jnp.logical_or(lu, _unwrap(rhs_fn())))


def convert_logical_not(x):
    u = _unwrap(x)
    if not _is_traced(u):
        return not bool(u)
    return Tensor(jnp.logical_not(u))


# --------------------------------------------------------------------------- #
# AST transformation                                                          #
# --------------------------------------------------------------------------- #

_BLOCKERS = (ast.Return, ast.Break, ast.Continue, ast.Yield, ast.YieldFrom)


def _scan(nodes):
    """True when return/break/continue/yield appears in `nodes` (stopping at
    nested function boundaries) — such blocks stay python (see module doc)."""
    for n in nodes:
        if isinstance(n, _BLOCKERS):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        for field in getattr(n, "_fields", ()):
            v = getattr(n, field, None)
            if isinstance(v, list):
                if _scan([x for x in v if isinstance(x, ast.AST)]):
                    return True
            elif isinstance(v, ast.AST):
                if _scan([v]):
                    return True
    return False


class _NameCollector(ast.NodeVisitor):
    def __init__(self):
        self.stored = set()
        self.loaded = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stored.add(node.id)
        else:
            self.loaded.add(node.id)

    def visit_FunctionDef(self, node):
        self.stored.add(node.name)  # don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _names(nodes):
    c = _NameCollector()
    for n in nodes:
        c.visit(n)
    return c.stored, c.loaded


class _TestTransformer(ast.NodeTransformer):
    """BoolOp/Not inside if/while tests → _jst.convert_logical_*."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="_jst", ctx=ast.Load()),
                    attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=expr),
                      ast.Lambda(args=_empty_args(), body=rhs)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="_jst", ctx=ast.Load()),
                    attr="convert_logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node

    def visit_Lambda(self, node):
        return node


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def visit_FunctionDef(self, node):
        return node  # don't transform nested defs

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def _emit_cluster(self, n, vars_, defs, call_expr):
        """Common tail: getter/resetter defs + result assignment."""
        stmts = list(defs)
        vt = ", ".join(vars_)
        if vars_:
            get_src = f"def __pt_get_{n}():\n    return ({vt},)"
            reset_src = (f"def __pt_reset_{n}(__pt_v):\n"
                         f"    nonlocal {vt}\n    ({vt},) = __pt_v")
            stmts += [ast.parse(get_src).body[0],
                      ast.parse(reset_src).body[0]]
            assign = ast.parse(f"({vt},) = {call_expr}").body[0]
        else:
            assign = ast.parse(call_expr).body[0]
        stmts.append(assign)
        return stmts

    def _guards(self, vars_):
        return [ast.parse(
            f"try:\n    {v}\nexcept NameError:\n    {v} = _jst.UNDEF"
        ).body[0] for v in vars_]

    def visit_If(self, node):
        self.generic_visit(node)
        if _scan(node.body) or _scan(node.orelse):
            return node  # return/break/continue inside: leave as python
        # only names ASSIGNED in a branch need capture/write-back; read-only
        # names stay plain closure reads (and plain python ints stay ints —
        # carrying them through lax.cond would trace-ify them)
        stored, _loaded = _names(node.body + node.orelse)
        vars_ = sorted(stored)
        n = self.counter
        self.counter += 1
        test = _TestTransformer().visit(node.test)
        ast.fix_missing_locations(test)
        test_src = ast.unparse(test)

        def mk_branch(name, body):
            body_src = "\n".join(ast.unparse(s) for s in body) or "pass"
            nl = f"    nonlocal {', '.join(vars_)}\n" if vars_ else ""
            src = f"def {name}():\n{nl}" + textwrap.indent(
                body_src, "    ")
            if not body:
                src = f"def {name}():\n{nl}    pass"
            return ast.parse(src).body[0]

        defs = self._guards(vars_) + [
            mk_branch(f"__pt_true_{n}", node.body),
            mk_branch(f"__pt_false_{n}", node.orelse)]
        get = f"__pt_get_{n}" if vars_ else "None"
        reset = f"__pt_reset_{n}" if vars_ else "None"
        call = (f"_jst.convert_ifelse(({test_src}), __pt_true_{n}, "
                f"__pt_false_{n}, {get}, {reset})")
        return self._emit_cluster(n, vars_, defs, call)

    def visit_For(self, node):
        """`for i in range(...)` lowers to the while machinery (ref
        dygraph_to_static loop_transformer's for->while rewrite); other
        iterables (lists, enumerate, tensors) stay python — range is the
        only form whose bound can be a traced Tensor."""
        self.generic_visit(node)
        if (node.orelse or _scan(node.body)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.iter.keywords
                        and 1 <= len(node.iter.args) <= 3)):
            return node
        n = self.counter   # unique suffix for the loop-state temporaries
        tgt = node.target.id
        args = [ast.unparse(a) for a in node.iter.args]
        if len(args) == 1:
            start, stop, step = "0", args[0], "1"
        elif len(args) == 2:
            start, stop, step = args[0], args[1], "1"
        else:
            start, stop, step = args
        # a hidden counter carries the loop; the TARGET is assigned inside
        # the body, so after the loop it holds the LAST value (python
        # binding), not one-past-the-end. Divergence kept: an empty range
        # leaves the target at `start` rather than unbound (a traced loop
        # needs a fixed carry structure).
        setup = ast.parse(
            f"__pt_i_{n} = {start}\n"
            f"{tgt} = __pt_i_{n}\n"
            f"__pt_stop_{n} = {stop}\n"
            f"__pt_step_{n} = _jst.check_step({step})").body
        # (stop - i) * step > 0 is direction-agnostic (positive or
        # negative traced step)
        while_src = (
            f"while (__pt_stop_{n} - __pt_i_{n}) * __pt_step_{n} > 0:\n"
            f"    pass")
        while_node = ast.parse(while_src).body[0]
        while_node.body = (
            ast.parse(f"{tgt} = __pt_i_{n}").body
            + list(node.body)
            + ast.parse(f"__pt_i_{n} = __pt_i_{n} + __pt_step_{n}").body)
        out = self.visit_While(while_node)
        return setup + (out if isinstance(out, list) else [out])

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _scan(node.body):
            return node
        stored, _loaded = _names(node.body)
        vars_ = sorted(stored)
        n = self.counter
        self.counter += 1
        test = _TestTransformer().visit(node.test)
        ast.fix_missing_locations(test)
        test_src = ast.unparse(test)
        nl = f"    nonlocal {', '.join(vars_)}\n" if vars_ else ""
        cond_src = f"def __pt_cond_{n}():\n    return ({test_src})"
        body_src = "\n".join(ast.unparse(s) for s in node.body) or "pass"
        body_def = f"def __pt_body_{n}():\n{nl}" + textwrap.indent(
            body_src, "    ")
        defs = self._guards(vars_) + [ast.parse(cond_src).body[0],
                                      ast.parse(body_def).body[0]]
        get = f"__pt_get_{n}" if vars_ else "None"
        reset = f"__pt_reset_{n}" if vars_ else "None"
        call = (f"_jst.convert_while(__pt_cond_{n}, __pt_body_{n}, "
                f"{get}, {reset})")
        return self._emit_cluster(n, vars_, defs, call)


_CACHE = {}


def convert_function(fn):
    """Rewrite `fn`'s tensor-dependent control flow; returns a new function
    closed over the same globals (ref program_translator.py:233
    ProgramTranslator + convert_to_static cache)."""
    # closure cells are baked into the converted copy's globals, so the cache
    # key must distinguish different closures over the same code object
    cells = tuple(fn.__closure__) if getattr(fn, "__closure__", None) else ()
    key = (getattr(fn, "__code__", None), cells)
    if key in _CACHE:
        return _CACHE[key]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fn_node = tree.body[0]
    if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fn_node.decorator_list = []
    def _range_for(nd):
        return (isinstance(nd, ast.For)
                and isinstance(nd.iter, ast.Call)
                and isinstance(nd.iter.func, ast.Name)
                and nd.iter.func.id == "range")

    has_cf = any(isinstance(s, (ast.If, ast.While)) or _range_for(s)
                 for s in ast.walk(fn_node))
    if not has_cf:
        _CACHE[key] = fn
        return fn
    tr = _ControlFlowTransformer()
    new_body = []
    for s in fn_node.body:
        out = tr.visit(s)
        if out is None:
            continue
        new_body.extend(out if isinstance(out, list) else [out])
    fn_node.body = new_body
    ast.fix_missing_locations(tree)

    glb = dict(fn.__globals__)
    glb["_jst"] = _JST
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    try:
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        exec(code, glb)
        new_fn = glb[fn_node.name]
    except SyntaxError as e:  # pragma: no cover - surface, keep original
        warnings.warn(f"dy2static: could not convert {fn.__qualname__}: {e}")
        _CACHE[key] = fn
        return fn
    new_fn = functools.wraps(fn)(new_fn)
    _CACHE[key] = new_fn
    return new_fn


class _JSTNamespace(types.SimpleNamespace):
    pass


_JST = _JSTNamespace(
    convert_ifelse=convert_ifelse,
    convert_while=convert_while,
    check_step=check_step,
    convert_logical_and=convert_logical_and,
    convert_logical_or=convert_logical_or,
    convert_logical_not=convert_logical_not,
    UNDEF=UNDEF,
)
