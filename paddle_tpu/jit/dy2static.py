"""dy2static — AST transpiler: tensor-dependent python control flow → lax.

TPU-native redesign of the reference dygraph_to_static stack
(ref python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py,
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
program_translator.py:233): the reference rewrites python AST into
ProgramDesc control-flow ops (conditional_block/while); here the same AST
surgery rewrites `if`/`while` statements into runtime helpers that pick
between plain python execution (concrete predicate — eager) and
`lax.cond`/`lax.while_loop` (traced predicate — inside jax.jit), so one
model source serves both programming models (SURVEY.md §7 P3).

Mechanics: each converted `if`/`while` becomes a cluster of nested
functions — branch bodies with `nonlocal` write-back, a getter and a
resetter for the captured variable tuple — mirroring the reference's
true_fn/false_fn + modified-name analysis (ifelse_transformer.py
NameVisitor), but without variable renaming because `nonlocal` gives
read/write access to the enclosing frame.

break/continue/return (ref break_continue_transformer.py,
return_transformer.py): lowered to loop-carried booleans BEFORE control-flow
conversion — `break` -> `__pt_brk_n = True` (loop test gains
`not __pt_brk_n`), `continue` -> `__pt_cont_n = True` (trailing body
statements guarded), `return v` -> `__pt_ret_flag/__pt_ret_val` sets with
every enclosing loop test gaining `not __pt_ret_flag` and the function tail
returning via _jst.finalize_return. The flags ride the normal lax carry, so
all three work under jit tracing.

Deliberate limits (same spirit as the reference's unsupported lists):
- `yield` blocks conversion (generators stay python);
- a TRACED early return must produce values of one consistent
  shape/dtype across all return sites (the reference's
  RETURN_NO_VALUE magic has the same constraint); eager returns are
  unrestricted;
- `for i in range(...)` lowers through the while machinery (tensor
  bounds become lax.while_loop; concrete ranges still unroll); other
  iterables (lists, enumerate, tensor iteration) stay python;
- variables flowing through converted control flow must be tensors/scalars
  when traced (strings/objects are closure-captured, branch-invariant).
"""
import ast
import functools
import inspect
import textwrap
import types
import warnings

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


# --------------------------------------------------------------------------- #
# runtime helpers (the `_jst` namespace emitted code calls into)              #
# --------------------------------------------------------------------------- #

class _Undef:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<UNDEF>"


UNDEF = _Undef()


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _any_traced(vals):
    return any(_is_traced(_unwrap(v)) for v in vals)


def _split_dynamic(vals):
    """Partition a variable tuple into (dynamic indices, static values).
    Dynamic = things lax can carry (tensors/arrays/python numbers)."""
    dyn_idx = []
    for i, v in enumerate(vals):
        u = _unwrap(v)
        if isinstance(u, (jax.Array, jax.core.Tracer, int, float, bool,
                          complex)) and not isinstance(v, _Undef):
            dyn_idx.append(i)
    return dyn_idx


def _scalar_pred(p):
    """lax.cond/while_loop need scalar bool preds; the fluid idiom is a
    shape-[1] tensor condition (fill_constant(shape=[1]) counters) —
    squeeze any size-1 pred to a scalar."""
    if _is_traced(p) and getattr(p, "ndim", 0) \
            and getattr(p, "size", None) == 1:
        return p.reshape(())
    return p


def _is_internal_placeholder(name):
    """Generated slots (return value/flag) whose not-assigned-branch value
    is never observed — safe to coerce to the assigned branch's aval."""
    return bool(name) and name.startswith("__pt_ret")


def _statics_equal(a, b):
    """Branch-agreement check for static values (strings, numbers,
    tuples, lists — possibly holding numpy arrays, whose elementwise ==
    would make bool() ambiguous)."""
    if a is b:
        return True
    import numpy as np
    if isinstance(a, (list, tuple)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_statics_equal(x, y) for x, y in zip(a, b)))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        try:
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        except (ValueError, TypeError):
            return False
    try:
        return bool(a == b)
    except (ValueError, TypeError):
        return False            # object compare failed: treat as unequal


def convert_ifelse(pred, true_fn, false_fn, get, reset, names=None):
    """Emitted for `if`: concrete pred runs one branch in place; traced pred
    lowers to lax.cond. Branch outputs are discovered during tracing: each
    branch closes over the enclosing frame (captured tracers become cond
    constants) and reports, per captured variable, whether it produced a
    dynamic value (carried through cond) or a static one (must agree across
    branches — same constraint the reference's ifelse_transformer imposes).
    Internal return-machinery slots get ONE reconciliation retry: the
    placeholder side coerces to zeros of the assigned side's aval (the ref
    RETURN_NO_VALUE contract — the value is only read when the flag says
    the assignment fired)."""
    p = _scalar_pred(_unwrap(pred))
    if not _is_traced(p):
        (true_fn if bool(p) else false_fn)()
        return get() if get is not None else ()
    if get is None:
        # no captured vars: still lower (branches may have jax side effects
        # like debug prints); outputs are empty
        jax.lax.cond(p, lambda _: (true_fn(), ())[1],
                     lambda _: (false_fn(), ())[1], None)
        return ()
    orig = get()
    # branch name -> list of ('dyn', aval, assigned) | ('static', v, assigned)
    specs = {}

    def run(fn, tag, coerce=None):
        def branch(_):
            reset(orig)
            fn()
            out = get()
            spec, leaves = [], []
            for i, v in enumerate(out):
                u = _unwrap(v)
                assigned = v is not orig[i]
                # list vars (ref list_transformer.py): carried through
                # cond element-wise ONLY for tensor content; scalar
                # lists (int shape/perm lists, even when assigned) stay
                # python statics — carrying them would tracer-ify values
                # downstream static-shape consumers need concrete.
                # Cross-branch disagreement of static lists is checked
                # after the cond instead.
                if _jaxable_list(u) and any(
                        isinstance(_unwrap(e),
                                   (jax.Array, jax.core.Tracer))
                        for e in u):
                    elems = [jnp.asarray(_unwrap(e)) for e in u]
                    spec.append(("list",
                                 tuple(jax.typeof(e) for e in elems),
                                 assigned,
                                 tuple(isinstance(e, Tensor)
                                       for e in u)))
                    leaves.extend(elems)
                    continue
                if isinstance(u, _TensorArrayCarry):
                    # tensor-array carry rides cond as (buf, length);
                    # the version count records this branch's appends
                    spec.append(("ta", u.version, assigned,
                                 (u.wrap, u.exact)))
                    leaves.extend([u.buf,
                                   jnp.asarray(u.length, jnp.int32)])
                    continue
                dyn = isinstance(u, (jax.Array, jax.core.Tracer)) or \
                    isinstance(u, (int, float, bool)) and \
                    not isinstance(v, _Undef)
                if coerce and i in coerce:
                    want = coerce[i]
                    leaf = jnp.asarray(u) if dyn else None
                    if leaf is None or jnp.shape(leaf) != want.shape \
                            or leaf.dtype != want.dtype:
                        leaf = jnp.zeros(want.shape, want.dtype)
                    spec.append(("dyn", jax.typeof(leaf), assigned))
                    leaves.append(leaf)
                elif dyn:
                    leaf = jnp.asarray(u)
                    spec.append(("dyn", jax.typeof(leaf), assigned))
                    leaves.append(leaf)
                else:
                    spec.append(("static", v, assigned))
            specs[tag] = spec
            return tuple(leaves)
        return branch

    def attempt(coerce=None):
        return jax.lax.cond(p, run(true_fn, "true", coerce),
                            run(false_fn, "false", coerce), None)

    try:
        res = attempt()
    except (TypeError, ValueError) as e:
        both = specs.get("true"), specs.get("false")
        coerce = {}
        mismatch = False
        if all(s is not None for s in both):
            for i, (st, sf) in enumerate(zip(*both)):
                if st[0] == sf[0] == "dyn" and st[1] == sf[1]:
                    continue
                if st[0] == sf[0] == "ta":
                    continue                # same structure by origin
                if st[0] == "ta" or sf[0] == "ta":
                    raise ValueError(
                        "dy2static: a list that grew inside the "
                        "enclosing loop is rebound inconsistently "
                        "across branches of a traced `if`") from e
                if st[0] == "list" or sf[0] == "list":
                    if st[0] == sf[0] == "list":
                        if st[1] == sf[1]:
                            continue        # identical: not the cause
                        nm = names[i] if names and i < len(names) \
                            else "a list"
                        raise ValueError(
                            f"dy2static: list {nm!r} has "
                            f"{len(st[1])} element(s) of "
                            f"{[str(a) for a in st[1]]} in the true "
                            f"branch but {len(sf[1])} of "
                            f"{[str(a) for a in sf[1]]} in the false "
                            "branch of a traced `if` — XLA needs one "
                            "structure; append consistently in both "
                            "branches") from e
                    raise ValueError(
                        "dy2static: a variable is a list in one branch "
                        "of a traced `if` but not the other — assign it "
                        "consistently in both branches") from e
                mismatch = True
                nm = names[i] if names and i < len(names) else None
                if not _is_internal_placeholder(nm):
                    continue
                # coerce ONLY a placeholder side (unassigned, or an
                # assigned static None — `return None` is the reference's
                # RETURN_NO_VALUE) to the dyn side's aval. Two branches
                # that both ASSIGN dyn values of different shapes is a
                # user error, not a placeholder artifact.
                def real(s):
                    return s[0] == "dyn" and s[2]
                if real(st) and real(sf):
                    raise ValueError(
                        "dy2static: `return` values under a traced "
                        "`if` have different shapes/dtypes across "
                        "branches — XLA needs one output type; return "
                        "consistently shaped values") from e
                target = [s[1] for s in (st, sf) if real(s)]
                if not target:
                    # neither side is a real assignment (`return None` vs
                    # the untouched placeholder): unify on any dyn aval
                    target = [s[1] for s in (st, sf) if s[0] == "dyn"]
                if target:
                    coerce[i] = target[0]
        if coerce:
            res = attempt(coerce)
        elif mismatch and any((st[0] == "dyn") != (sf[0] == "dyn")
                              for st, sf in zip(*both)):
            # branches disagree on which USER vars are tensors:
            # lax.cond's generic pytree error, made actionable
            raise ValueError(
                "dy2static: a variable is a tensor in one branch of a "
                "traced `if` but not the other — assign it consistently "
                "in both branches") from e
        else:
            raise
    spec_t, spec_f = specs["true"], specs["false"]
    for i, (st, sf) in enumerate(zip(spec_t, spec_f)):
        if st[0] != sf[0] and {"list", "ta"} & {st[0], sf[0]}:
            raise ValueError(
                "dy2static: a variable is a list in one branch of a "
                "traced `if` but not the other — assign it consistently "
                "in both branches")
        if (st[0] == "dyn") != (sf[0] == "dyn"):
            raise ValueError(
                "dy2static: a variable is a tensor in one branch of a "
                "traced `if` but not the other — assign it consistently "
                "in both branches")
        nm = names[i] if names and i < len(names) else None
        if (st[0] == sf[0] == "static" and (st[2] or sf[2])
                # only USER constants: the cluster machinery assigns its
                # generated nested defs (__pt_*) branch-locally, and a
                # one-sided assignment (other side undefined) keeps the
                # longstanding closure semantics
                and not (nm or "__pt_").startswith("__pt_")
                and not (callable(st[1]) or callable(sf[1]))
                and not isinstance(st[1], _Undef)
                and not isinstance(sf[1], _Undef)
                and not _statics_equal(st[1], sf[1])):
            raise ValueError(
                f"dy2static: {nm!r} is assigned different python "
                f"values across branches of a traced `if` "
                f"({st[1]!r} vs {sf[1]!r}) — a python constant cannot "
                "be selected at runtime; use tensors, or assign the "
                "same value in both branches")
    final, j = [], 0
    for i, s in enumerate(spec_t):
        if s[0] == "dyn":
            final.append(Tensor(res[j]) if isinstance(orig[i], Tensor)
                         or isinstance(orig[i], _Undef) else res[j])
            j += 1
        elif s[0] == "list":
            k = len(s[1])
            # wrap a slot as Tensor if EITHER branch held a Tensor there
            # (the structural check compares avals, not wrappers)
            wf = spec_f[i][3] if spec_f[i][0] == "list" else s[3]
            final.append([Tensor(leaf) if (w or w2) else leaf
                          for leaf, w, w2 in zip(res[j:j + k], s[3], wf)])
            j += k
        elif s[0] == "ta":
            sf = spec_f[i]
            # uneven branch growth -> the traced length diverges from
            # the append count: the final length is data-dependent, so
            # exact finalization is off (honest-limit error on stack())
            even = s[1] == sf[1]
            wrap, exact = s[3]
            final.append(_TensorArrayCarry(
                res[j], res[j + 1], wrap,
                exact and even and sf[3][1],
                max(s[1], sf[1])))
            j += 2
        else:
            final.append(s[1])
    reset(tuple(final))
    return tuple(final)


def convert_while(cond_fn, body_fn, get, reset, names=None, bound=None):
    """Emitted for `while`: concrete → python loop; traced condition or
    loop vars → lax.while_loop over the dynamic subset of captured vars
    (static vars are loop-invariant closure constants). `names` is the
    captured-variable name tuple (diagnostics + the generated-local
    exemption below). `bound` (for->while lowerings only) is a thunk
    returning the CURRENT (i, stop, step) — the static trip bound that
    caps tensor-array list carries.

    The python loop re-checks tracedness EVERY iteration and escapes to the
    lax path mid-loop from the current state: a loop can start fully
    concrete and only acquire a traced carry later (e.g. a return/break
    flag set by a traced `if` — the break_continue/return transforms)."""
    while True:
        c = _unwrap(cond_fn())
        cur = get() if get is not None else ()
        if _is_traced(c) or _any_traced(cur):
            return _lax_while_lists(cond_fn, body_fn, get, reset, cur,
                                    names, bound)
        if not bool(c):
            return cur
        body_fn()


def _nm(names, i):
    return names[i] if names and i < len(names) else f"var{i}"


def _remaining_trips(bound):
    """Static iteration cap of a lowered for-range loop, from the CURRENT
    loop state; None when any of (i, stop, step) is traced."""
    if bound is None:
        return None
    import math
    cur, stop, step = (_unwrap(v) for v in bound())
    if any(_is_traced(v) for v in (cur, stop, step)):
        return None
    return max(0, math.ceil((stop - cur) / step))


def _lax_scan(body_fn, get, reset, orig, names, trips):
    """Fixed-trip lowering: a for-range loop with NO early-exit/skip
    flags runs exactly `trips` iterations, so it lowers to lax.scan —
    which, unlike lax.while_loop, supports reverse-mode AD. This is the
    path that makes dy2static-converted training forwards (teacher-
    forced decoders etc.) differentiable end to end."""
    dyn_idx = _split_dynamic(orig)

    def put(carry):
        full = list(orig)
        for j, i in enumerate(dyn_idx):
            full[i] = Tensor(carry[j]) if isinstance(orig[i], Tensor) \
                else carry[j]
        reset(tuple(full))

    def step(carry, _):
        put(carry)
        body_fn()
        out = get()
        for i, v in enumerate(out):
            if i not in dyn_idx and _is_traced(_unwrap(v)) \
                    and not _is_traced(_unwrap(orig[i])) \
                    and not isinstance(orig[i], _Undef):
                nm = names[i] if names and i < len(names) else None
                what = f"variable {nm!r}" if nm else "a variable"
                raise ValueError(
                    f"dy2static: {what} becomes a tensor inside a traced "
                    "loop — initialize it as a tensor before the loop "
                    "(XLA loop carries need a fixed structure)")
        new = []
        for j, i in enumerate(dyn_idx):
            u = jnp.asarray(_unwrap(out[i]))
            new.append(u.astype(carry[j].dtype)
                       if u.dtype != carry[j].dtype else u)
        return tuple(new), None

    carry0 = tuple(jnp.asarray(_unwrap(orig[i])) for i in dyn_idx)
    res, _ = jax.lax.scan(step, carry0, None, length=trips)
    final = list(orig)
    for j, i in enumerate(dyn_idx):
        final[i] = Tensor(res[j]) if isinstance(orig[i], Tensor) else res[j]
    for i, v in enumerate(final):
        if isinstance(v, _Undef):
            final[i] = _LoopLocal(names[i] if names and i < len(names)
                                  else None)
    reset(tuple(final))
    return tuple(final)


def _lax_while_lists(cond_fn, body_fn, get, reset, orig, names, bound=None):
    """List-carry adapter over _lax_while (ref list_transformer.py's
    tensor-array writes): each jaxable list var expands to per-element
    carry slots; a list that grows raises _ListGrew during the first
    trace and retries with a fixed-capacity _TensorArrayCarry, capacity =
    current length + the loop's remaining static trips."""
    # fixed-trip loops (static range bound, no break/continue/return
    # flags) lower to lax.scan — the differentiable path; everything
    # else keeps lax.while_loop semantics
    exact = not any(
        n and n.startswith(("__pt_brk", "__pt_cont", "__pt_ret"))
        for n in (names or ()))
    trips = _remaining_trips(bound)
    if trips is not None and exact:
        def run(bf, g, r, o, n):
            return _lax_scan(bf, g, r, o, n, trips)
    else:
        def run(bf, g, r, o, n):
            return _lax_while(cond_fn, bf, g, r, o, n)

    list_idx = [i for i, v in enumerate(orig)
                if _jaxable_list(v) or isinstance(v, _TensorArrayCarry)]
    if not list_idx:
        return run(body_fn, get, reset, orig, names)

    # var index -> ("elems", length, wrap_flags) | ("ta", wrap, exact)
    mode = {}
    for i in list_idx:
        v = orig[i]
        if isinstance(v, _TensorArrayCarry):      # nested lowered loop
            mode[i] = ("ta", v.wrap, v.exact)
        else:
            mode[i] = ("elems", len(v), tuple(isinstance(e, Tensor)
                                              for e in v))

    def expand(vals):
        out, nm = [], []
        for i, v in enumerate(vals):
            if i not in mode:
                out.append(v)
                nm.append(_nm(names, i))
                continue
            m = mode[i]
            if m[0] == "elems":
                if not isinstance(v, list):
                    raise ValueError(
                        f"dy2static: list {_nm(names, i)!r} was rebound "
                        "to a non-list inside a traced loop")
                if len(v) != m[1]:
                    u = jnp.asarray(_unwrap(v[-1])) if v else None
                    raise _ListGrew(
                        i, len(v),
                        tuple(u.shape) if u is not None else None,
                        str(u.dtype) if u is not None else None,
                        bool(v and isinstance(v[-1], Tensor)))
                out.extend(v)
                nm.extend(f"{_nm(names, i)}[{k}]" for k in range(m[1]))
            else:
                if not isinstance(v, _TensorArrayCarry):
                    raise ValueError(
                        f"dy2static: list {_nm(names, i)!r} was "
                        "reassigned inside a traced loop after growing — "
                        "build it in one place")
                if not v.exact and m[2]:
                    # a traced `if` appended unevenly: final length is
                    # data-dependent; sticky for the rest of the loop
                    mode[i] = m = ("ta", m[1], False)
                out.extend([v.buf, jnp.asarray(v.length, jnp.int32)])
                nm.extend([f"{_nm(names, i)}.buf",
                           f"{_nm(names, i)}.len"])
        return tuple(out), tuple(nm)

    def collapse(vals):
        out, j = [], 0
        for i in range(len(orig)):
            if i not in mode:
                out.append(vals[j])
                j += 1
                continue
            m = mode[i]
            if m[0] == "elems":
                elems = vals[j:j + m[1]]
                j += m[1]
                out.append([Tensor(_unwrap(e))
                            if w and not isinstance(e, Tensor) else e
                            for e, w in zip(elems, m[2])])
            else:
                buf, ln = vals[j], vals[j + 1]
                j += 2
                out.append(_TensorArrayCarry(jnp.asarray(_unwrap(buf)),
                                             _unwrap(ln), m[1], m[2]))
        return tuple(out)

    def get2():
        return expand(get())[0]

    def reset2(vals):
        reset(collapse(vals))

    while True:
        orig2, names2 = expand(orig)
        try:
            res2 = run(body_fn, get2, reset2, orig2, names2)
        except _ListGrew as g:
            if trips is None:
                raise ValueError(
                    f"dy2static: list {_nm(names, g.idx)!r} grows inside "
                    "a traced loop with no static trip bound — XLA needs "
                    "a fixed capacity. Use `for i in range(...)` with "
                    "concrete bounds, or preallocate with paddle.zeros "
                    "and index-write (ref list_transformer.py lowers "
                    "this to LoDTensorArray, which is host-dynamic; a "
                    "TPU loop carry cannot be)") from None
            entry = orig[g.idx]
            # growth detected at body END: new_len - entry counts the
            # appends of ONE iteration (k > 1 when the body appends
            # several times; uneven cond-appends already error in the
            # list-spec check), so capacity = entry + k per remaining trip
            per_iter = max(1, g.new_len - len(entry))
            cap = len(entry) + trips * per_iter
            if g.elem_shape is None:
                raise ValueError(
                    f"dy2static: cannot infer element shape for list "
                    f"{_nm(names, g.idx)!r} (grew from empty with no "
                    "appended element visible)") from None
            buf = jnp.zeros((cap,) + g.elem_shape, g.elem_dtype)
            for k, e in enumerate(entry):
                buf = buf.at[k].set(jnp.asarray(_unwrap(e))
                                    .astype(buf.dtype))
            ta = _TensorArrayCarry(buf, len(entry), g.wrap, exact)
            mode[g.idx] = ("ta", g.wrap, exact)
            lst = list(orig)
            lst[g.idx] = ta
            orig = tuple(lst)
            reset(orig)
            continue
        break

    # finalize: exact tensor-array carries become plain python lists of
    # their capacity elements — downstream stack/concat/len/indexing all
    # behave like the reference's tensor_array_to_tensor results
    final = list(collapse(res2))
    changed = False
    for i, m in mode.items():
        v = final[i]
        if isinstance(v, _TensorArrayCarry) and v.exact:
            final[i] = [Tensor(v.buf[k]) if v.wrap else v.buf[k]
                        for k in range(v.capacity)]
            changed = True
    final = tuple(final)
    if changed:
        reset(final)
    return final


def _lax_while(cond_fn, body_fn, get, reset, orig, names=None):
    dyn_idx = _split_dynamic(orig)
    body_avals = {}        # var index -> aval the body actually produced

    def put(carry):
        full = list(orig)
        for j, i in enumerate(dyn_idx):
            full[i] = Tensor(carry[j]) if isinstance(orig[i], Tensor) \
                else carry[j]
        reset(tuple(full))

    def c(carry):
        put(carry)
        return _scalar_pred(_unwrap(cond_fn()))

    def b(carry):
        put(carry)
        body_fn()
        out = get()
        for i, v in enumerate(out):
            if i not in dyn_idx and _is_traced(_unwrap(v)) \
                    and not _is_traced(_unwrap(orig[i])) \
                    and not isinstance(orig[i], _Undef):
                # a var that WAS undefined at loop entry is a loop-LOCAL
                # (written fresh every iteration — nested-loop counters,
                # break flags, cluster helpers); it needs no carry slot
                # and is POISONED after the loop (see _LoopLocal below).
                # Only a real pre-loop static turning traced is an error.
                nm = names[i] if names and i < len(names) else None
                what = f"variable {nm!r}" if nm else "a variable"
                raise ValueError(
                    f"dy2static: {what} becomes a tensor inside a traced "
                    "`while` body — initialize it as a tensor before the "
                    "loop (XLA loop carries need a fixed structure)")
        new = []
        for j, i in enumerate(dyn_idx):
            u = jnp.asarray(_unwrap(out[i]))
            body_avals[i] = jax.typeof(u)
            new.append(u.astype(carry[j].dtype)
                       if u.dtype != carry[j].dtype else u)
        return tuple(new)

    carry0 = tuple(jnp.asarray(_unwrap(orig[i])) for i in dyn_idx)
    try:
        res = jax.lax.while_loop(c, b, carry0)
    except (TypeError, ValueError):
        # return-machinery placeholders enter the loop as scalar 0.0 but
        # the body assigns the real return value's shape/dtype — coerce
        # the ENTRY carry to the body's aval (zeros; only read when the
        # return flag fired) and retry once. Only the UNTOUCHED 0.0
        # placeholder qualifies: a traced entry value means an earlier
        # `return` already produced a real value of a different shape,
        # which no fixed carry can represent.
        carry0l, origl = list(carry0), list(orig)
        coerced = False
        for j, i in enumerate(dyn_idx):
            nm = names[i] if names and i < len(names) else None
            want = body_avals.get(i)
            have = jax.typeof(carry0l[j])
            if not (_is_internal_placeholder(nm) and want is not None
                    and (want.shape, want.dtype)
                    != (have.shape, have.dtype)):
                continue
            # provenance check on the RAW pre-asarray value: the untouched
            # placeholder is the python float 0.0 the return transformer
            # emitted, with the return FLAG still the python False it was
            # initialized to. In NESTED lowered loops the outer carry
            # turns the placeholder into a scalar tracer before the inner
            # loop sees it, so a scalar-()-shaped slot widening to a
            # shaped body value is also accepted as a placeholder.
            # Known approximation: an earlier traced `return <scalar>`
            # followed by a loop `return <shaped>` coerces the scalar
            # away (zeros) instead of erroring — the runtime-dependent
            # return STRUCTURE XLA cannot represent anyway.
            raw = _unwrap(orig[i])
            flag_raw = False
            if names and "__pt_ret_flag" in names:
                flag_raw = _unwrap(orig[names.index("__pt_ret_flag")])
            is_placeholder = (
                (isinstance(raw, float) and raw == 0.0
                 and flag_raw is False)
                or (_is_traced(raw) and jnp.shape(raw) == ()
                    and want.ndim > 0))
            if not is_placeholder:
                raise ValueError(
                    "dy2static: `return` values on different paths "
                    "through a traced loop have different shapes/dtypes "
                    "— XLA needs one output type; return consistently "
                    "shaped values")
            z = jnp.zeros(want.shape, want.dtype)
            carry0l[j] = z
            origl[i] = Tensor(z) if isinstance(orig[i], Tensor) else z
            coerced = True
        if not coerced:
            raise
        orig = tuple(origl)           # put()/b() close over this name
        carry0 = tuple(carry0l)
        res = jax.lax.while_loop(c, b, carry0)
    final = list(orig)
    for j, i in enumerate(dyn_idx):
        final[i] = Tensor(res[j]) if isinstance(orig[i], Tensor) else res[j]
    # loop-locals (UNDEF at entry, no carry slot): their per-iteration
    # values cannot escape the while_loop scope — poison them so a
    # post-loop READ fails with the variable's name instead of silently
    # propagating a sentinel
    for i, v in enumerate(final):
        if isinstance(v, _Undef):
            final[i] = _LoopLocal(names[i] if names and i < len(names)
                                  else None)
    reset(tuple(final))
    return tuple(final)


class _LoopLocal:
    """Post-loop value of a variable first assigned INSIDE a traced loop:
    lax.while_loop scopes its carry, so the value cannot escape. Any use
    raises with the variable's name; never using it (generated counters,
    flags, inner-loop targets) is fine."""

    def __init__(self, name):
        object.__setattr__(self, "_pt_name", name or "<unknown>")

    def _pt_die(self, *a, **k):
        raise ValueError(
            f"dy2static: variable {self._pt_name!r} was first assigned "
            "inside a traced loop; its value does not escape the "
            "lax.while_loop — initialize it before the loop to read it "
            "afterwards")

    def __getattr__(self, name):
        self._pt_die()

    def __repr__(self):
        return f"<loop-local {self._pt_name!r}>"

    __bool__ = __call__ = __iter__ = __len__ = __getitem__ = _pt_die
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _pt_die
    __truediv__ = __rtruediv__ = __eq__ = __lt__ = __gt__ = _pt_die
    __float__ = __int__ = __index__ = _pt_die


def check_step(step):
    """range() semantics: a CONCRETE zero step is an error (python raises
    ValueError); a traced step can't be checked at trace time."""
    u = _unwrap(step)
    if not _is_traced(u) and int(u) == 0:
        raise ValueError("range() arg 3 must not be zero")
    return step


# --------------------------------------------------------------------------- #
# list lowering (ref dygraph_to_static/list_transformer.py +                  #
# loop_transformer.py tensor-array paths, redesigned for XLA semantics):      #
# `x.append(v)` is rewritten to `x = _jst.convert_list_append(x, v)` so list  #
# mutation is a name-store the branch/loop capture machinery carries.         #
# Fixed-length lists ride lax carries element-wise; a list that GROWS inside  #
# a traced loop becomes a _TensorArrayCarry — a preallocated [capacity, ...]  #
# HBM buffer + running length (XLA has no dynamic allocation; the capacity    #
# comes from the loop's static trip bound). The reference's LoDTensorArray    #
# is host-side dynamic, so its writes are unbounded; the static-capacity      #
# contract is the honest TPU equivalent.                                      #
# --------------------------------------------------------------------------- #


def _jaxable_elem(e):
    u = _unwrap(e)
    return isinstance(u, (jax.Array, jax.core.Tracer,
                          int, float, bool, complex))


def _jaxable_list(v):
    return isinstance(v, list) and all(_jaxable_elem(e) for e in v)


class _ListGrew(Exception):
    """A list var changed length inside a traced loop body: retry the
    loop with a tensor-array carry (shape/dtype captured at raise time —
    the element tracers die with the abandoned trace)."""

    def __init__(self, idx, new_len, elem_shape, elem_dtype, wrap):
        super().__init__(idx)
        self.idx = idx
        self.new_len = new_len
        self.elem_shape = elem_shape
        self.elem_dtype = elem_dtype
        self.wrap = wrap


class _TensorArrayCarry:
    """A list growing inside a traced loop: [capacity, *elem] buffer +
    running length, written via dynamic_update_slice. `exact` marks loops
    with no early-exit/skip flags, where the final length provably equals
    the capacity and the value finalizes back to a plain python list."""

    def __init__(self, buf, length, wrap, exact, version=0):
        self.buf = buf
        self.length = length
        self.wrap = wrap
        self.exact = exact
        # python-side append count since the last carry rebuild: lets
        # convert_ifelse compare branch growth STATICALLY (the traced
        # lengths are opaque) and demote `exact` on uneven appends
        self.version = version

    @property
    def capacity(self):
        return self.buf.shape[0]

    def append(self, v):
        u = jnp.asarray(_unwrap(v))
        if tuple(u.shape) != tuple(self.buf.shape[1:]):
            raise ValueError(
                "dy2static: appended element shape "
                f"{tuple(u.shape)} != earlier elements' "
                f"{tuple(self.buf.shape[1:])} — a list lowered to a "
                "tensor-array needs uniform elements")
        buf = jax.lax.dynamic_update_slice_in_dim(
            self.buf, u.astype(self.buf.dtype)[None],
            jnp.asarray(self.length, jnp.int32), axis=0)
        return _TensorArrayCarry(buf, self.length + 1, self.wrap,
                                 self.exact, self.version + 1)

    def __getitem__(self, i):
        ix = jnp.asarray(_unwrap(i), jnp.int32)
        # negative indices count from the RUNNING length, not the
        # preallocated capacity (x[-1] must be the last APPENDED value)
        ix = jnp.where(ix < 0, ix + jnp.asarray(self.length, jnp.int32),
                       ix)
        v = self.buf[ix]
        return Tensor(v) if self.wrap else v

    def _no_static_len(self, *a, **k):
        raise ValueError(
            "dy2static: this list grew inside a traced loop with "
            "break/continue/return, so its final length is a traced "
            "value; index it with x[i] (traced index ok) or read "
            "_jst.convert_len(x), but it cannot become a python list — "
            "restructure without early exit, or preallocate with "
            "paddle.zeros and index-write")

    __len__ = __iter__ = _no_static_len


def convert_list_append(xs, v):
    """`x.append(v)` -> `x = convert_list_append(x, v)`. Returns a NEW
    list (value semantics: branch purity and carry snapshots need the
    pre-append value intact) or a tensor-array write inside traced
    loops."""
    if isinstance(xs, _TensorArrayCarry):
        return xs.append(v)
    if isinstance(xs, list):
        return xs + [v]
    xs.append(v)          # TensorArray static API / user object
    return xs


def convert_list_pop(xs, idx=-1):
    """`v = x.pop(i)` -> `x, v = convert_list_pop(x, i)`."""
    if isinstance(xs, _TensorArrayCarry):
        raise ValueError(
            "dy2static: pop() on a list that grew inside a traced loop "
            "is not representable in XLA — restructure without pop")
    i = _unwrap(idx)
    if isinstance(xs, list):
        if _is_traced(i):
            raise ValueError(
                "dy2static: list.pop(i) with a tensor index — use a "
                "concrete index, or tensor indexing on a stacked tensor")
        new = list(xs)
        return new, new.pop(int(i))
    return xs, xs.pop(i)


def convert_list_pop_(xs, idx=-1):
    """Statement-position pop: value discarded."""
    return convert_list_pop(xs, idx)[0]


def convert_list_setitem(xs, idx, v):
    """`x[i] = v` -> `x = convert_list_setitem(x, i, v)`. A traced index
    into a real list selects element-wise (the list stays a python list
    of uniform tensors)."""
    if not isinstance(xs, list):
        xs[idx] = v       # Tensor / dict / user object: native setitem
        return xs
    i = _unwrap(idx)
    if _is_traced(i):
        if not xs or not all(_jaxable_elem(e) for e in xs):
            raise ValueError(
                "dy2static: tensor-index write needs a non-empty list "
                "of tensors")
        # python negative-index semantics (the matching load path's
        # stack[i] gather already wraps; the equal() sweep must agree)
        i = jnp.where(i < 0, i + len(xs), i)
        u = jnp.asarray(_unwrap(v))
        out = []
        for k, e in enumerate(xs):
            old = jnp.asarray(_unwrap(e))
            new = jnp.where(jnp.equal(i, k), u.astype(old.dtype), old)
            out.append(Tensor(new) if isinstance(e, Tensor) else new)
        return out
    new = list(xs)
    new[int(i) if not isinstance(i, int) else i] = v
    return new


def convert_list_getitem(xs, idx):
    """Load-position `x[i]` for known-list names: traced index gathers
    from the stacked elements."""
    if isinstance(xs, _TensorArrayCarry):
        return xs[idx]
    i = _unwrap(idx)
    if isinstance(xs, list) and _is_traced(i):
        if not xs or not all(_jaxable_elem(e) for e in xs):
            raise ValueError(
                "dy2static: tensor index into a non-tensor list")
        stack = jnp.stack([jnp.asarray(_unwrap(e)) for e in xs])
        v = stack[jnp.asarray(i, jnp.int32)]
        return Tensor(v) if isinstance(xs[0], Tensor) else v
    if isinstance(xs, list) and isinstance(i, jax.Array):
        i = int(i)
    return xs[i if isinstance(xs, list) else idx]


def convert_list_insert(xs, idx, v):
    """`x.insert(i, v)` -> `x = convert_list_insert(x, i, v)`."""
    if isinstance(xs, _TensorArrayCarry):
        raise ValueError(
            "dy2static: insert() on a list that grew inside a traced "
            "loop is not representable in XLA (it shifts the written "
            "slots) — append in order instead")
    i = _unwrap(idx)
    if isinstance(xs, list):
        if _is_traced(i):
            raise ValueError(
                "dy2static: list.insert with a tensor index — use a "
                "concrete index")
        new = list(xs)
        new.insert(int(i), v)
        return new
    xs.insert(i, v)
    return xs


def convert_list_extend(xs, other):
    """`x.extend(o)` -> `x = convert_list_extend(x, o)`."""
    if isinstance(xs, _TensorArrayCarry):
        out = xs
        for e in list(other):
            out = out.append(e)
        return out
    if isinstance(xs, list):
        return xs + list(other)
    xs.extend(other)
    return xs


def convert_list_clear(xs):
    """`x.clear()` -> `x = convert_list_clear(x)`."""
    if isinstance(xs, _TensorArrayCarry):
        raise ValueError(
            "dy2static: clear() on a list that grew inside a traced "
            "loop — an XLA loop carry needs a fixed structure")
    if isinstance(xs, list):
        return []
    xs.clear()
    return xs


def convert_len(x):
    """len() in converted code (ref convert_call len -> array_length):
    python len for containers, static leading dim for tensors, the
    running (possibly traced) length for tensor-array carries."""
    if isinstance(x, _TensorArrayCarry):
        return Tensor(jnp.asarray(x.length)) if x.wrap else x.length
    u = _unwrap(x)
    if isinstance(u, (jax.Array, jax.core.Tracer)):
        if u.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return u.shape[0]
    return len(x)


def is_tensor_seq(x):
    u = _unwrap(x)
    return isinstance(u, (jax.Array, jax.core.Tracer)) \
        and getattr(u, "ndim", 0) >= 1


def seq_len(x):
    return int(_unwrap(x).shape[0])


def convert_print(*args, **kwargs):
    """Emitted for `print(...)` (ref dygraph_to_static print_transformer:
    print -> Print op so output happens at every EXECUTION, not once at
    trace time). Traced arguments route through jax.debug.print honoring
    sep/end (the debug printer always newline-terminates — a non-default
    `end` is emitted before that newline); fully concrete calls stay
    python print."""
    if any(_is_traced(_unwrap(a)) for a in args):
        sep = kwargs.get("sep", " ")
        end = kwargs.get("end", "\n")
        fmt = sep.join("{}" for _ in args)
        if end != "\n":
            fmt += end
        jax.debug.print(fmt, *[_unwrap(a) for a in args],
                        ordered=bool(kwargs.get("ordered", False)))
        return
    print(*args, **kwargs)


def convert_assert(pred, msg=None):
    """Emitted for `assert` (ref dygraph_to_static assert_transformer:
    assert -> Assert op, which halts at runtime). Concrete preds stay
    python asserts; traced preds install an ordered debug callback that
    raises when the executed value is False — surfacing as a runtime
    error on the step that violated the assertion."""
    p = _scalar_pred(_unwrap(pred))
    if not _is_traced(p):
        assert bool(p), msg if msg is not None else "assert failed"
        return

    def cb(v):
        if not bool(v):
            raise AssertionError(
                msg if msg is not None
                else "dy2static: traced assert failed")

    jax.debug.callback(cb, p, ordered=True)


def _cast_dtype(kind):
    # through the framework's dtype normalization (int64 -> int32 when
    # x64 is off) so traces don't spew truncation warnings
    from ..framework.dtype import convert_dtype
    return convert_dtype({"int": "int64", "float": "float32",
                          "bool": "bool"}[kind])


def convert_cast(kind, x):
    """Emitted for int(x)/float(x)/bool(x) (ref dygraph_to_static
    cast_transformer: python casts -> the cast op). A traced tensor
    becomes an astype (scalar tensors only, like the reference); python
    values keep python semantics."""
    u = _unwrap(x)
    if _is_traced(u):
        if getattr(u, "size", 1) != 1:
            raise ValueError(
                f"dy2static: {kind}() on a traced tensor of shape "
                f"{jnp.shape(u)} — python casts apply to scalars; use "
                f".astype() for arrays")
        out = jnp.reshape(u, ()).astype(_cast_dtype(kind))
        return Tensor(out) if isinstance(x, Tensor) else out
    return {"int": int, "float": float, "bool": bool}[kind](x)


def convert_logical_and(lhs_fn, rhs_fn):
    """ref logical_transformer.py convert_logical_and — preserves python
    short-circuit when concrete."""
    l = lhs_fn()
    lu = _unwrap(l)
    if not _is_traced(lu):
        if not bool(lu):
            return l
        return rhs_fn()
    return Tensor(jnp.logical_and(lu, _unwrap(rhs_fn())))


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    lu = _unwrap(l)
    if not _is_traced(lu):
        if bool(lu):
            return l
        return rhs_fn()
    return Tensor(jnp.logical_or(lu, _unwrap(rhs_fn())))


def convert_logical_not(x):
    u = _unwrap(x)
    if not _is_traced(u):
        return not bool(u)
    return Tensor(jnp.logical_not(u))


def finalize_return(flag, val):
    """Function tail after the return transform: a concrete never-set flag
    means python fall-off-the-end semantics (None); a traced flag means at
    least one traced return site executed — the carried val IS the result
    (sites that didn't fire left the initial 0.0, matching the reference's
    RETURN_NO_VALUE contract that all traced paths return)."""
    u = _unwrap(flag)
    if not _is_traced(u):
        return val if bool(u) else None
    return val


# --------------------------------------------------------------------------- #
# AST transformation                                                          #
# --------------------------------------------------------------------------- #

# statements that keep a block python when they SURVIVE the pre-passes
# (the break/continue/return transformers remove the ones they can lower;
# leftovers — yields, returns in unlowerable loops — must block conversion
# or convert_ifelse would silently discard them)
_BLOCKERS = (ast.Return, ast.Break, ast.Continue, ast.Yield, ast.YieldFrom)


def _scan_for(kinds, nodes, stop_at_loops=False):
    """True when a node of `kinds` appears in `nodes`, stopping at nested
    function boundaries (and optionally at nested loops — break/continue
    bind to the nearest loop)."""
    for n in nodes:
        if isinstance(n, kinds):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if stop_at_loops and isinstance(n, (ast.For, ast.While)):
            continue
        for field in getattr(n, "_fields", ()):
            v = getattr(n, field, None)
            if isinstance(v, list):
                if _scan_for(kinds, [x for x in v if isinstance(x, ast.AST)],
                             stop_at_loops):
                    return True
            elif isinstance(v, ast.AST):
                if _scan_for(kinds, [v], stop_at_loops):
                    return True
    return False


def _scan(nodes):
    """True when a surviving blocker statement appears in `nodes` — such
    blocks stay python (see _BLOCKERS)."""
    return _scan_for(_BLOCKERS, nodes)


def _sets_name(stmt, names):
    """Does this statement subtree assign any of `names`? (Flag names are
    generated uniques, so a plain Name-target search is exact.)"""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id in names:
                    return True
    return False


def _guard_tail(stmts, flags):
    """ref break_continue_transformer.py BreakContinueTransformer: after any
    statement that may set one of `flags`, wrap the remaining statements in
    `if not (f1 or f2 ...):` — the lowered form of the skipped tail."""
    names = set(flags)
    out = []
    for idx, s in enumerate(stmts):
        out.append(s)
        if _sets_name(s, names) and idx < len(stmts) - 1:
            rest = _guard_tail(stmts[idx + 1:], flags)
            test_src = " or ".join(flags)
            guard = ast.parse(f"if not ({test_src}):\n    pass").body[0]
            guard.body = rest
            out.append(guard)
            return out
    return out


def _apply_guards_in_lists(node, flags, *, into_loops):
    """Run _guard_tail over every statement list under `node` (not crossing
    nested function boundaries; optionally not crossing loop boundaries)."""
    for field in getattr(node, "_fields", ()):
        v = getattr(node, field, None)
        if isinstance(v, list) and v and all(isinstance(x, ast.stmt)
                                             for x in v):
            for x in v:
                if isinstance(x, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                if not into_loops and isinstance(x, (ast.For, ast.While)):
                    continue
                _apply_guards_in_lists(x, flags, into_loops=into_loops)
            setattr(node, field, _guard_tail(v, flags))
        elif isinstance(v, ast.AST):
            if isinstance(v, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if not into_loops and isinstance(v, (ast.For, ast.While)):
                continue
            _apply_guards_in_lists(v, flags, into_loops=into_loops)


def _not_flag_test(test, flag):
    """`test` -> `(not flag) and (test)` as AST."""
    return ast.BoolOp(op=ast.And(), values=[
        ast.UnaryOp(op=ast.Not(),
                    operand=ast.Name(id=flag, ctx=ast.Load())),
        test])


def _loop_convertible(node):
    """Syntactic lowering eligibility (mirrors _ControlFlowTransformer's
    For/While acceptance). NOT sufficient on its own — see
    _loop_will_lower."""
    if isinstance(node, ast.While):
        return not node.orelse
    return (not node.orelse
            and isinstance(node.target, ast.Name)
            and isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and not node.iter.keywords
            and 1 <= len(node.iter.args) <= 3)


def _direct_nested_loops(nodes):
    """Outermost For/While nodes under `nodes`, not crossing function
    boundaries and not descending into found loops."""
    out = []
    for n in nodes:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, (ast.For, ast.While)):
            out.append(n)
            continue
        for field in getattr(n, "_fields", ()):
            v = getattr(n, field, None)
            kids = v if isinstance(v, list) else [v]
            out += _direct_nested_loops(
                [x for x in kids if isinstance(x, ast.AST)])
    return out


def _loop_will_lower(node):
    """Will this loop ACTUALLY be lowered once the pre-passes run? Lowered
    loops are the only legal flag consumers (their tests gain `not flag`
    terms); a loop the control-flow transformer ends up leaving as python
    (because a blocker survives inside it) must keep its literal
    break/continue/return. A loop lowers iff it is syntactically
    convertible, contains no yield, and every nested loop holding flow
    statements will itself lower (those are the only blockers the
    pre-passes cannot remove)."""
    if not _loop_convertible(node):
        return False
    if _scan_for((ast.Yield, ast.YieldFrom), node.body):
        return False
    for nl in _direct_nested_loops(node.body):
        if _scan_for((ast.Break, ast.Continue, ast.Return), [nl]) \
                and not _loop_will_lower(nl):
            return False
    return True


class _BreakContinueReplacer(ast.NodeTransformer):
    """Replace break/continue bound to THE CURRENT loop with flag sets
    (does not descend into nested loops or functions)."""

    def __init__(self, brk, cont):
        self.brk, self.cont = brk, cont
        self.saw_brk = self.saw_cont = False

    def visit_Break(self, node):
        self.saw_brk = True
        return ast.parse(f"{self.brk} = True").body[0]

    def visit_Continue(self, node):
        self.saw_cont = True
        return ast.parse(f"{self.cont} = True").body[0]

    def visit_For(self, node):
        return node

    def visit_While(self, node):
        return node

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


class _BreakContinueTransformer(ast.NodeTransformer):
    """ref dygraph_to_static/break_continue_transformer.py, lowered for the
    lax world: break/continue become loop-carried booleans. Bottom-up, so
    inner loops are clean before the enclosing loop is processed."""

    def __init__(self):
        self.counter = 0

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def _rewrite(self, node):
        """Shared For/While body rewrite. Returns (prelude_stmts, node) —
        prelude initialises the break flag BEFORE the loop."""
        self.generic_visit(node)      # inner loops first
        if not _loop_will_lower(node):
            # stays a python loop: literal break/continue keep working;
            # flag-lowering would break them (no test hook to exit)
            return [], node
        if not _scan_for((ast.Break, ast.Continue), node.body,
                         stop_at_loops=True):
            return [], node
        n = self.counter
        self.counter += 1
        brk, cont = f"__pt_brk_{n}", f"__pt_cont_{n}"
        rep = _BreakContinueReplacer(brk, cont)
        node.body = [rep.visit(s) for s in node.body]
        flags = [f for f, saw in ((brk, rep.saw_brk), (cont, rep.saw_cont))
                 if saw]
        _apply_guards_in_lists(node, flags, into_loops=False)
        prelude = []
        if rep.saw_cont:
            node.body = ast.parse(f"{cont} = False").body + node.body
            prelude += ast.parse(f"{cont} = False").body
        if rep.saw_brk:
            prelude += ast.parse(f"{brk} = False").body
            if isinstance(node, ast.While):
                node.test = _not_flag_test(node.test, brk)
            else:   # For: the for->while lowering reads this marker
                node._pt_extra_break_flags = (
                    getattr(node, "_pt_extra_break_flags", []) + [brk])
        return prelude, node

    def visit_While(self, node):
        prelude, node = self._rewrite(node)
        return prelude + [node] if prelude else node

    def visit_For(self, node):
        prelude, node = self._rewrite(node)
        return prelude + [node] if prelude else node


class _ReturnTransformer(ast.NodeTransformer):
    """ref dygraph_to_static/return_transformer.py: every `return v` becomes
    `__pt_ret_flag = True; __pt_ret_val = v`; trailing statements are
    guarded on the flag; every loop on the path gains `not __pt_ret_flag`
    in its test; the function tail returns _jst.finalize_return(...)."""

    FLAG, VAL = "__pt_ret_flag", "__pt_ret_val"

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def visit_Return(self, node):
        # val BEFORE flag: the guard pass wraps everything after a
        # flag-setting statement, and the companion val assignment must
        # stay unguarded
        val_src = ast.unparse(node.value) if node.value is not None else "None"
        return ast.parse(f"{self.VAL} = ({val_src})\n"
                         f"{self.FLAG} = True").body

    def visit_While(self, node):
        self.generic_visit(node)
        if _sets_name(node, {self.FLAG}):
            node.test = _not_flag_test(node.test, self.FLAG)
        return node

    def visit_For(self, node):
        self.generic_visit(node)
        if _sets_name(node, {self.FLAG}):
            node._pt_extra_break_flags = (
                getattr(node, "_pt_extra_break_flags", []) + [self.FLAG])
        return node

    @classmethod
    def apply(cls, fn_node):
        """Transform iff a return appears INSIDE a compound statement — any
        container (if/while/for/try/with), not just direct top-level control
        flow (a plain top-level `return` needs nothing). Returns True when
        applied. Bails (returns False, leaving returns literal) when a
        return sits inside a loop that will NOT be lowered: such loops stay
        python and must keep their real `return`."""
        nested = any(not isinstance(s, ast.Return)
                     and _scan_for((ast.Return,), [s])
                     for s in fn_node.body)
        if not nested:
            return False

        def _unlowerable_return(nodes):
            # walk without crossing nested-function boundaries
            for n in nodes:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                if isinstance(n, (ast.For, ast.While)) \
                        and not _loop_will_lower(n) \
                        and _scan_for((ast.Return,), n.body):
                    return True
                for field in getattr(n, "_fields", ()):
                    v = getattr(n, field, None)
                    kids = (v if isinstance(v, list) else [v])
                    kids = [x for x in kids if isinstance(x, ast.AST)]
                    if kids and _unlowerable_return(kids):
                        return True
            return False

        if _unlowerable_return(fn_node.body):
            return False
        tr = cls()
        new_body = []
        for s in fn_node.body:
            out = tr.visit(s)
            new_body.extend(out if isinstance(out, list) else [out])
        # guard every trailing statement list on the flag, at every depth
        holder = ast.Module(body=new_body, type_ignores=[])
        _apply_guards_in_lists(holder, [cls.FLAG], into_loops=True)
        fn_node.body = (
            ast.parse(f"{cls.FLAG} = False\n{cls.VAL} = 0.0").body
            + holder.body
            + ast.parse(
                f"return _jst.finalize_return({cls.FLAG}, {cls.VAL})").body)
        return True


class _NameCollector(ast.NodeVisitor):
    def __init__(self):
        self.stored = set()
        self.loaded = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stored.add(node.id)
        else:
            self.loaded.add(node.id)

    def visit_FunctionDef(self, node):
        self.stored.add(node.name)  # don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _names(nodes):
    c = _NameCollector()
    for n in nodes:
        c.visit(n)
    return c.stored, c.loaded


class _TestTransformer(ast.NodeTransformer):
    """BoolOp/Not inside if/while tests → _jst.convert_logical_*."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="_jst", ctx=ast.Load()),
                    attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=expr),
                      ast.Lambda(args=_empty_args(), body=rhs)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="_jst", ctx=ast.Load()),
                    attr="convert_logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node

    def visit_Lambda(self, node):
        return node


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


class _ListCollector(ast.NodeVisitor):
    """Names ever bound to a list display / comprehension / list() call
    in this function body (ref list_transformer.py's created-list
    tracking) — only these names get the method-call rewrites, so
    `.append`/`.pop` on arbitrary objects keeps native semantics."""

    def __init__(self):
        self.names = set()

    @staticmethod
    def _is_list_value(v):
        return isinstance(v, (ast.List, ast.ListComp)) or (
            isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
            and v.func.id == "list")

    def visit_Assign(self, node):
        if self._is_list_value(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.names.add(t.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        # `a: list = []` creates a list just like a plain assign
        if node.value is not None and self._is_list_value(node.value) \
                and isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass                     # nested defs own their names

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _reloc_stmt(src, node):
    """Parse one synthetic statement and stamp it with `node`'s source
    location (the runtime error map keeps pointing at user lines)."""
    out = ast.parse(src).body[0]
    for sub in ast.walk(out):
        ast.copy_location(sub, node)
    return out


class _ListTransformer(ast.NodeTransformer):
    """ref dygraph_to_static/list_transformer.py: list mutation becomes
    name-stores (`x = _jst.convert_list_append(x, v)` …) so the
    branch/loop capture machinery carries the list like any other
    variable; loads `x[i]` route through convert_list_getitem so a
    traced index gathers from the stacked elements."""

    def __init__(self, names):
        self.names = names

    def _is_list_name(self, nd):
        return isinstance(nd, ast.Name) and nd.id in self.names

    _stmt = staticmethod(_reloc_stmt)

    def visit_Expr(self, node):
        self.generic_visit(node)
        v = node.value
        if not (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and self._is_list_name(v.func.value) and not v.keywords):
            return node
        x = v.func.value.id
        args = [ast.unparse(a) for a in v.args]
        if v.func.attr == "append" and len(args) == 1:
            return self._stmt(
                f"{x} = _jst.convert_list_append({x}, {args[0]})", node)
        if v.func.attr == "pop" and len(args) <= 1:
            a = f", {args[0]}" if args else ""
            return self._stmt(
                f"{x} = _jst.convert_list_pop_({x}{a})", node)
        if v.func.attr == "insert" and len(args) == 2:
            return self._stmt(
                f"{x} = _jst.convert_list_insert({x}, {args[0]}, "
                f"{args[1]})", node)
        if v.func.attr == "extend" and len(args) == 1:
            return self._stmt(
                f"{x} = _jst.convert_list_extend({x}, {args[0]})", node)
        if v.func.attr == "clear" and not args:
            return self._stmt(
                f"{x} = _jst.convert_list_clear({x})", node)
        return node

    def visit_AugAssign(self, node):
        # x[i] op= v  ->  x = setitem(x, i, getitem(x, i) op v)
        self.generic_visit(node)
        t = node.target
        if not (isinstance(t, ast.Subscript) and self._is_list_name(t.value)
                and not isinstance(t.slice, ast.Slice)):
            return node
        ops = {ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Div: "/",
               ast.FloorDiv: "//", ast.Mod: "%", ast.Pow: "**",
               ast.MatMult: "@"}
        op = ops.get(type(node.op))
        if op is None:
            return node
        x, idx = t.value.id, ast.unparse(t.slice)
        return self._stmt(
            f"{x} = _jst.convert_list_setitem({x}, {idx}, "
            f"_jst.convert_list_getitem({x}, {idx}) {op} "
            f"({ast.unparse(node.value)}))", node)

    def visit_Delete(self, node):
        # del x[i] -> x = convert_list_pop_(x, i)
        self.generic_visit(node)
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and self._is_list_name(node.targets[0].value)
                and not isinstance(node.targets[0].slice, ast.Slice)):
            t = node.targets[0]
            return self._stmt(
                f"{t.value.id} = _jst.convert_list_pop_({t.value.id}, "
                f"{ast.unparse(t.slice)})", node)
        return node

    def visit_Assign(self, node):
        self.generic_visit(node)
        v = node.value
        # v = x.pop(...)
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "pop"
                and self._is_list_name(v.func.value) and not v.keywords
                and len(v.args) <= 1 and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            x = v.func.value.id
            a = f", {ast.unparse(v.args[0])}" if v.args else ""
            return self._stmt(
                f"({x}, {node.targets[0].id}) = "
                f"_jst.convert_list_pop({x}{a})", node)
        # x[i] = v
        t = node.targets[0] if len(node.targets) == 1 else None
        if (isinstance(t, ast.Subscript) and self._is_list_name(t.value)
                and not isinstance(t.slice, ast.Slice)):
            x = t.value.id
            return self._stmt(
                f"{x} = _jst.convert_list_setitem({x}, "
                f"{ast.unparse(t.slice)}, {ast.unparse(v)})", node)
        return node

    def visit_Subscript(self, node):
        self.generic_visit(node)
        if (isinstance(node.ctx, ast.Load)
                and self._is_list_name(node.value)
                and not isinstance(node.slice, ast.Slice)):
            new = ast.parse(
                f"_jst.convert_list_getitem({node.value.id}, "
                f"{ast.unparse(node.slice)})", mode="eval").body
            for sub in ast.walk(new):
                ast.copy_location(sub, node)
            return new
        return node

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


_PY_ITER_CALLS = {"enumerate", "zip", "list", "tuple", "set", "sorted",
                  "reversed", "dict", "map", "filter"}


def _obviously_python_iter(nd, list_names=()):
    """Iterables that can never be tensors: skip the tensor-for dispatch
    (its body duplication and cluster overhead buy nothing there)."""
    if isinstance(nd, (ast.List, ast.Tuple, ast.Set, ast.Dict,
                       ast.ListComp, ast.GeneratorExp, ast.DictComp,
                       ast.SetComp)):
        return True
    if isinstance(nd, ast.Constant):
        return True
    if isinstance(nd, ast.Name) and nd.id in list_names:
        return True
    if isinstance(nd, ast.Call):
        f = nd.func
        if isinstance(f, ast.Name) and f.id in _PY_ITER_CALLS:
            return True
        if isinstance(f, ast.Attribute) and f.attr in (
                "items", "keys", "values", "split", "splitlines"):
            return True
    return False


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, list_names=()):
        self.counter = 0
        self.list_names = frozenset(list_names)
        self._iter_dispatches = 0

    def visit_FunctionDef(self, node):
        return node  # don't transform nested defs

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    @staticmethod
    def _restore_locs(new_stmts, old_stmts):
        """Copy source locations from original statements onto their
        unparse->reparse equivalents (the runtime error source map:
        lowered branch/loop bodies keep the user's line numbers).
        Structures match by construction; best-effort on drift."""
        for new, old in zip(new_stmts, old_stmts):
            for a, b in zip(ast.walk(new), ast.walk(old)):
                if type(a) is not type(b):
                    break
                if hasattr(b, "lineno") \
                        and "lineno" in getattr(a, "_attributes", ()):
                    ast.copy_location(a, b)

    def _emit_cluster(self, n, vars_, defs, call_expr):
        """Common tail: getter/resetter defs + result assignment."""
        stmts = list(defs)
        vt = ", ".join(vars_)
        if vars_:
            get_src = f"def __pt_get_{n}():\n    return ({vt},)"
            reset_src = (f"def __pt_reset_{n}(__pt_v):\n"
                         f"    nonlocal {vt}\n    ({vt},) = __pt_v")
            stmts += [ast.parse(get_src).body[0],
                      ast.parse(reset_src).body[0]]
            assign = ast.parse(f"({vt},) = {call_expr}").body[0]
        else:
            assign = ast.parse(call_expr).body[0]
        stmts.append(assign)
        return stmts

    def _guards(self, vars_):
        return [ast.parse(
            f"try:\n    {v}\nexcept NameError:\n    {v} = _jst.UNDEF"
        ).body[0] for v in vars_]

    def visit_If(self, node):
        self.generic_visit(node)
        if _scan(node.body) or _scan(node.orelse):
            return node  # return/break/continue inside: leave as python
        # only names ASSIGNED in a branch need capture/write-back; read-only
        # names stay plain closure reads (and plain python ints stay ints —
        # carrying them through lax.cond would trace-ify them)
        stored, _loaded = _names(node.body + node.orelse)
        vars_ = sorted(stored)
        n = self.counter
        self.counter += 1
        test = _TestTransformer().visit(node.test)
        ast.fix_missing_locations(test)
        test_src = ast.unparse(test)

        def mk_branch(name, body):
            body_src = "\n".join(ast.unparse(s) for s in body) or "pass"
            nl = f"    nonlocal {', '.join(vars_)}\n" if vars_ else ""
            src = f"def {name}():\n{nl}" + textwrap.indent(
                body_src, "    ")
            if not body:
                src = f"def {name}():\n{nl}    pass"
            fn_def = ast.parse(src).body[0]
            if body:
                off = 1 if vars_ else 0        # skip the nonlocal stmt
                self._restore_locs(fn_def.body[off:], body)
            return fn_def

        defs = self._guards(vars_) + [
            mk_branch(f"__pt_true_{n}", node.body),
            mk_branch(f"__pt_false_{n}", node.orelse)]
        get = f"__pt_get_{n}" if vars_ else "None"
        reset = f"__pt_reset_{n}" if vars_ else "None"
        names_lit = "(" + "".join(f"{v!r}, " for v in vars_) + ")"
        call = (f"_jst.convert_ifelse(({test_src}), __pt_true_{n}, "
                f"__pt_false_{n}, {get}, {reset}, names={names_lit})")
        return self._emit_cluster(n, vars_, defs, call)

    def visit_For(self, node):
        """`for i in range(...)` lowers to the while machinery (ref
        dygraph_to_static loop_transformer's for->while rewrite).
        `for t in <expr>` over a TENSOR lowers to an index loop over the
        static leading dim (ref loop_transformer's for-iter rewrite) via
        a runtime dispatch — python iterables keep python semantics.
        Loops carrying raw break/continue/return stay python."""
        if getattr(node, "_pt_no_lower", False):
            return node          # the python-fallback arm of a dispatch
        before = self._iter_dispatches
        self.generic_visit(node)
        if (node.orelse or _scan(node.body)
                or not isinstance(node.target, ast.Name)):
            return node
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and not node.iter.keywords
                and 1 <= len(node.iter.args) <= 3):
            if (_obviously_python_iter(node.iter, self.list_names)
                    or self._iter_dispatches > before):
                # python-only iterable, or a NESTED for-each already
                # dispatched inside this body: duplicating it again
                # would grow the converted function exponentially —
                # innermost loops get the tensor dispatch, outer levels
                # stay python (tensor rows still iterate eagerly there)
                return node
            return self._lower_iter_for(node)
        n = self.counter   # unique suffix for the loop-state temporaries
        tgt = node.target.id
        args = [ast.unparse(a) for a in node.iter.args]
        if len(args) == 1:
            start, stop, step = "0", args[0], "1"
        elif len(args) == 2:
            start, stop, step = args[0], args[1], "1"
        else:
            start, stop, step = args
        # a hidden counter carries the loop; the TARGET is assigned inside
        # the body, so after the loop it holds the LAST value (python
        # binding), not one-past-the-end. Divergence kept: an empty range
        # leaves the target at `start` rather than unbound (a traced loop
        # needs a fixed carry structure).
        setup = ast.parse(
            f"__pt_i_{n} = {start}\n"
            f"{tgt} = __pt_i_{n}\n"
            f"__pt_stop_{n} = {stop}\n"
            f"__pt_step_{n} = _jst.check_step({step})").body
        # (stop - i) * step > 0 is direction-agnostic (positive or
        # negative traced step); break/return flags attached by the
        # pre-passes join the test here
        extra = "".join(
            f" and not {f}"
            for f in getattr(node, "_pt_extra_break_flags", ()))
        while_src = (
            f"while (__pt_stop_{n} - __pt_i_{n}) * __pt_step_{n} > 0"
            f"{extra}:\n"
            f"    pass")
        while_node = ast.parse(while_src).body[0]
        while_node.body = (
            ast.parse(f"{tgt} = __pt_i_{n}").body
            + list(node.body)
            + ast.parse(f"__pt_i_{n} = __pt_i_{n} + __pt_step_{n}").body)
        # static trip bound for tensor-array list carries: evaluated at
        # lax-escape time from the CURRENT loop state
        while_node._pt_bound_expr = (
            f"lambda: (__pt_i_{n}, __pt_stop_{n}, __pt_step_{n})")
        out = self.visit_While(while_node)
        return setup + (out if isinstance(out, list) else [out])

    def _lower_iter_for(self, node):
        """`for t in seq:` -> runtime dispatch: a tensor seq becomes an
        index loop over its static leading dim (then lowered through the
        range machinery — traced-state bodies ride lax.while with a
        dynamic row slice); anything else stays a python for."""
        n = self.counter
        self.counter += 1
        self._iter_dispatches += 1
        tgt = node.target.id
        seq = f"__pt_seq_{n}"
        setup = _reloc_stmt(f"{seq} = {ast.unparse(node.iter)}", node)
        import copy
        skel = (f"if _jst.is_tensor_seq({seq}):\n"
                f"    for __pt_it_{n} in range(_jst.seq_len({seq})):\n"
                f"        {tgt} = {seq}[__pt_it_{n}]\n"
                f"        pass\n"
                f"else:\n"
                f"    for {tgt} in {seq}:\n"
                f"        pass\n")
        disp = ast.parse(skel).body[0]
        for sub in ast.walk(disp):
            ast.copy_location(sub, node)
        tfor, pfor = disp.body[0], disp.orelse[0]
        tfor.body = tfor.body[:1] + [copy.deepcopy(s) for s in node.body]
        pfor.body = list(node.body)
        pfor._pt_no_lower = True
        out = self.visit_If(disp)
        return [setup] + (out if isinstance(out, list) else [out])

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _scan(node.body):
            return node
        stored, _loaded = _names(node.body)
        vars_ = sorted(stored)
        n = self.counter
        self.counter += 1
        test = _TestTransformer().visit(node.test)
        ast.fix_missing_locations(test)
        test_src = ast.unparse(test)
        nl = f"    nonlocal {', '.join(vars_)}\n" if vars_ else ""
        cond_src = f"def __pt_cond_{n}():\n    return ({test_src})"
        body_src = "\n".join(ast.unparse(s) for s in node.body) or "pass"
        body_def = f"def __pt_body_{n}():\n{nl}" + textwrap.indent(
            body_src, "    ")
        body_node = ast.parse(body_def).body[0]
        off = 1 if vars_ else 0                # skip the nonlocal stmt
        self._restore_locs(body_node.body[off:], node.body)
        defs = self._guards(vars_) + [ast.parse(cond_src).body[0],
                                      body_node]
        get = f"__pt_get_{n}" if vars_ else "None"
        reset = f"__pt_reset_{n}" if vars_ else "None"
        names_lit = ("(" + ", ".join(repr(v) for v in vars_) + ",)"
                     if vars_ else "None")
        bound = getattr(node, "_pt_bound_expr", "None")
        call = (f"_jst.convert_while(__pt_cond_{n}, __pt_body_{n}, "
                f"{get}, {reset}, names={names_lit}, bound={bound})")
        return self._emit_cluster(n, vars_, defs, call)


def _is_cast_call(nd):
    return (isinstance(nd, ast.Call) and isinstance(nd.func, ast.Name)
            and nd.func.id in ("int", "float", "bool")
            and len(nd.args) == 1 and not nd.keywords)


class _CallsiteTransformer(ast.NodeTransformer):
    """print -> convert_print (output at every execution), assert ->
    convert_assert (runtime halt), int/float/bool -> convert_cast (the
    reference's print/assert/cast transformers)."""

    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            node.func = ast.Attribute(
                value=ast.Name(id="_jst", ctx=ast.Load()),
                attr="convert_print", ctx=ast.Load())
        elif (isinstance(node.func, ast.Name) and node.func.id == "len"
                and len(node.args) == 1 and not node.keywords):
            # len -> convert_len (ref convert_call's len->array_length):
            # python len for containers, static dim for tensors, running
            # length for tensor-array carries
            node.func = ast.Attribute(
                value=ast.Name(id="_jst", ctx=ast.Load()),
                attr="convert_len", ctx=ast.Load())
        elif _is_cast_call(node):
            node.args = [ast.copy_location(
                ast.Constant(value=node.func.id), node)] + node.args
            node.func = ast.Attribute(
                value=ast.Name(id="_jst", ctx=ast.Load()),
                attr="convert_cast", ctx=ast.Load())
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test]
        if node.msg is not None:
            args.append(node.msg)
        call = ast.Call(
            func=ast.Attribute(
                value=ast.Name(id="_jst", ctx=ast.Load()),
                attr="convert_assert", ctx=ast.Load()),
            args=args, keywords=[])
        return ast.copy_location(
            ast.Expr(value=ast.copy_location(call, node)), node)


_CACHE = {}


def convert_function(fn):
    """Rewrite `fn`'s tensor-dependent control flow; returns a new function
    closed over the same globals (ref program_translator.py:233
    ProgramTranslator + convert_to_static cache)."""
    # bound methods: convert the underlying function, re-bind to the
    # instance (paddle allows to_static(layer.forward) too)
    if inspect.ismethod(fn):
        conv = convert_function(fn.__func__)
        return types.MethodType(conv, fn.__self__) \
            if conv is not fn.__func__ else fn
    # closure cells are baked into the converted copy's globals, so the cache
    # key must distinguish different closures over the same code object AND
    # different CONTENTS of the same cell (a nonlocal rebind after first
    # conversion must re-bake, not serve the stale copy). Cells are
    # unhashable (they define __eq__ since 3.8): key by (cell id, content
    # id); the cache value pins the cells so the ids stay valid.
    cells = tuple(fn.__closure__) if getattr(fn, "__closure__", None) else ()

    def _content_id(c):
        try:
            return id(c.cell_contents)
        except ValueError:          # empty cell
            return None

    key = (getattr(fn, "__code__", None),
           tuple((id(c), _content_id(c)) for c in cells))
    # pin the CURRENT contents too: a freed old content's id could be
    # reused by a new object, which would false-hit the stale entry
    pins = (cells, tuple(c.cell_contents if _content_id(c) is not None
                         else None for c in cells))
    if key in _CACHE:
        return _CACHE[key][0]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fn_node = tree.body[0]
    if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fn_node.decorator_list = []
    # runtime error source map: shift the (dedented) tree back to the
    # function's true location so the converted code object carries the
    # ORIGINAL line numbers, and compile under the original filename —
    # a traceback raised inside a lowered loop/branch body then points
    # at the user's source line, not at rewritten synthetic code (ref
    # dygraph_to_static/error.py's OriginInfo map; here the code object
    # itself is the map)
    first_line = getattr(getattr(fn, "__code__", None), "co_firstlineno", 1)
    if first_line > 1:
        ast.increment_lineno(tree, first_line - 1)
    src_file = None
    try:
        src_file = inspect.getsourcefile(fn)
    except TypeError:
        pass
    def _is_print(nd):
        return (isinstance(nd, ast.Call) and isinstance(nd.func, ast.Name)
                and nd.func.id == "print")

    lc = _ListCollector()
    for s in fn_node.body:
        lc.visit(s)
    # list USE (indexing/mutation of a created-list name) also needs the
    # runtime helpers — a tensor index into a list works only converted
    has_list_use = lc.names and any(
        (isinstance(s, ast.Subscript) and isinstance(s.value, ast.Name)
         and s.value.id in lc.names)
        or (isinstance(s, ast.Attribute) and isinstance(s.value, ast.Name)
            and s.value.id in lc.names
            and s.attr in ("append", "pop"))
        for s in ast.walk(fn_node))
    has_cf = any(isinstance(s, (ast.If, ast.While, ast.Assert, ast.For))
                 or _is_print(s) or _is_cast_call(s)
                 for s in ast.walk(fn_node))
    if not (has_cf or has_list_use):
        _CACHE[key] = (fn, pins)
        return fn
    # list mutation -> name-stores the capture machinery can carry (ref
    # list_transformer.py); runs FIRST so appends/pops count as stored
    # names for every later pass. Applied statement-wise: the passes'
    # FunctionDef guards protect NESTED defs, not this top-level one.
    if lc.names:
        lt = _ListTransformer(lc.names)
        fn_node.body = [lt.visit(s) for s in fn_node.body]
    # print/assert/cast -> per-execution runtime forms (ref
    # print_transformer.py / assert_transformer.py / cast_transformer.py)
    _CallsiteTransformer().visit(fn_node)

    # pre-passes: return -> flag/val, break/continue -> loop-carried booleans
    # (ref return_transformer.py / break_continue_transformer.py)
    _ReturnTransformer.apply(fn_node)
    bc = _BreakContinueTransformer()
    bc_body = []
    for s in fn_node.body:
        out = bc.visit(s)
        bc_body.extend(out if isinstance(out, list) else [out])
    fn_node.body = bc_body
    tr = _ControlFlowTransformer(list_names=lc.names)
    new_body = []
    for s in fn_node.body:
        out = tr.visit(s)
        if out is None:
            continue
        new_body.extend(out if isinstance(out, list) else [out])
    fn_node.body = new_body
    ast.fix_missing_locations(tree)
    if first_line > 1:
        # synthetic nodes were mini-parsed with lines 1..k, which the
        # shifted original lines can never be — restamp them with the
        # nearest enclosing ORIGINAL line so every traceback frame in
        # converted code lands on a real user source line
        def stamp(node, cur):
            ln = getattr(node, "lineno", None)
            if ln is not None:
                if ln >= first_line:
                    cur = ln
                else:
                    node.lineno = cur
                    node.col_offset = 0
            if getattr(node, "end_lineno", None) is not None \
                    and node.end_lineno < first_line:
                node.end_lineno = cur
                node.end_col_offset = 0
            for child in ast.iter_child_nodes(node):
                stamp(child, cur)
        stamp(fn_node, first_line)

    glb = dict(fn.__globals__)
    glb["_jst"] = _JST
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    try:
        code = compile(
            tree,
            filename=src_file or f"<dy2static {fn.__qualname__}>",
            mode="exec")
        exec(code, glb)
        new_fn = glb[fn_node.name]
    except SyntaxError as e:  # pragma: no cover - surface, keep original
        warnings.warn(f"dy2static: could not convert {fn.__qualname__}: {e}")
        _CACHE[key] = (fn, pins)
        return fn
    new_fn = functools.wraps(fn)(new_fn)
    _CACHE[key] = (new_fn, pins)
    return new_fn


class _JSTNamespace(types.SimpleNamespace):
    pass


_JST = _JSTNamespace(
    convert_ifelse=convert_ifelse,
    convert_while=convert_while,
    check_step=check_step,
    convert_logical_and=convert_logical_and,
    convert_logical_or=convert_logical_or,
    convert_logical_not=convert_logical_not,
    convert_print=convert_print,
    convert_assert=convert_assert,
    convert_cast=convert_cast,
    finalize_return=finalize_return,
    convert_list_append=convert_list_append,
    convert_list_pop=convert_list_pop,
    convert_list_pop_=convert_list_pop_,
    convert_list_setitem=convert_list_setitem,
    convert_list_getitem=convert_list_getitem,
    convert_list_insert=convert_list_insert,
    convert_list_extend=convert_list_extend,
    convert_list_clear=convert_list_clear,
    convert_len=convert_len,
    is_tensor_seq=is_tensor_seq,
    seq_len=seq_len,
    UNDEF=UNDEF,
)
