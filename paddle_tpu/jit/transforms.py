"""Strategy transforms -> compiled-step rewrites.

The consumption point for fleet.DistributedStrategy: each meta-optimizer
(distributed/fleet/meta_optimizers.py) records its feature in
``optimizer.transforms``; the compiled train steps (jit.TrainStep,
distributed.sharded.ShardedTrainStep) call into here so the flags actually
change execution — the TPU-native analog of the reference meta-optimizers
rewriting the ProgramDesc (ref fleet/base/fleet_base.py:1070 chained via
base/strategy_compiler.py:89, e.g. sharding_optimizer.py:100,
amp_optimizer.py, recompute_optimizer.py):

  amp            -> bf16 autocast (O1: white/black-list casts inside the
                    traced forward via the dispatch amp state; O2: params
                    cast to bf16 for compute, fp32 masters kept for the
                    update — ref mixed_precision master-weight semantics)
  recompute      -> jax.checkpoint over the forward (rematerialize in bwd)
  gradient_merge -> in-step k-step gradient accumulation under lax.cond
  sharding       -> ZeRO stage for ShardedTrainStep (opt-state/dp sharding)
  localsgd       -> LocalSGDTrainStep (distributed/localsgd.py)
  pipeline       -> PipelineTrainStep (distributed/pipeline.py)
"""
import jax
import jax.numpy as jnp

from ..framework import state


def resolve(optimizer):
    """The transform dict accumulated by the meta-optimizer chain."""
    return dict(getattr(optimizer, "transforms", None) or {})


def reduced_dtype(value, default=jnp.float16):
    """Normalize a user-facing dtype spec ('float16'/'bf16'/np.dtype/
    jnp dtype object) to the jnp reduced-precision dtype."""
    import numpy as np
    if value is None:
        return default
    try:
        dt = jnp.dtype(value)
    except TypeError:
        s = str(value)
        if s.endswith(("bfloat16", "bf16")):
            return jnp.bfloat16
        if s.endswith(("float16", "fp16", "half")):
            return jnp.float16
        raise ValueError(f"unrecognized reduced dtype {value!r}")
    if dt == jnp.dtype(jnp.bfloat16):
        return jnp.bfloat16
    if dt == np.dtype(np.float16):
        return jnp.float16
    raise ValueError(f"unsupported reduced dtype {value!r}")


def wrap_forward(forward, transforms):
    """Apply amp/recompute to a functional forward
    ``forward(params, buffers, key, inputs, labels) -> (loss, aux)``.
    Order: autocast innermost, checkpoint outermost (the rematerialized
    segment must replay the same casts)."""
    amp = transforms.get("amp")
    if amp:
        level = amp.get("level", "O1")
        low = reduced_dtype(amp.get("dtype"), default=jnp.bfloat16)
        inner = forward

        def amp_forward(p, buffers, key, inputs, labels):
            if level == "O2":
                # compute in low precision, master weights stay fp32 —
                # the cast is differentiable so grads return as fp32
                p = jax.tree.map(
                    lambda a: a.astype(low)
                    if a.dtype == jnp.float32 else a, p)
            with state.amp_guard_ctx({"level": level, "dtype": low}):
                return inner(p, buffers, key, inputs, labels)

        forward = amp_forward
    rc = transforms.get("recompute")
    if rc is not None:
        forward = jax.checkpoint(forward, policy=_remat_policy(rc))
    return forward


def _remat_policy(rc_config):
    """Map the recompute strategy's `policy` knob to a jax.checkpoint
    policy. Default (None) is full rematerialization — max memory
    saving, forward runs ~twice. "dots" saves every matmul/contraction
    output and replays only the cheap elementwise chains: ~half the
    recompute FLOPs for most of the activation-memory win on
    matmul-dominated models (ref recompute_configs has no analog knob —
    the XLA policy machinery is the TPU-native upgrade)."""
    pol = (rc_config or {}).get("policy")
    if pol in (None, "", "full"):
        return None
    if pol == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown recompute policy {pol!r} "
                     "(expected 'full' or 'dots')")


def merge_config(transforms):
    """(k_steps, avg) for in-step gradient accumulation."""
    gm = transforms.get("gradient_merge") or {}
    return max(1, int(gm.get("k_steps", 1) or 1)), bool(gm.get("avg", True))


def zero_stage_of(transforms, default=0):
    """ZeRO stage implied by the sharding transform (ref
    sharding_optimizer.py 'sharding_degree'/'stage' configs)."""
    sh = transforms.get("sharding")
    if sh is None:
        return default
    return int(sh.get("stage", 1) or 1)


def merged_update(apply_fn, k_steps, avg):
    """Wrap an optimizer apply_fn with k-step gradient accumulation:
    returns ``update(params, grads, opt_state, acc, lr, step_i) ->
    (new_params, new_opt, new_acc)``. With k_steps == 1 the accumulator
    is a zero-leaf passthrough."""

    if k_steps <= 1:
        def update1(params, grads, opt_state, acc, lr, step_i):
            new_params, new_opt = apply_fn(params, grads, opt_state, lr,
                                           step_i)
            return new_params, new_opt, acc
        return update1

    def update(params, grads, opt_state, acc, lr, step_i):
        acc = jax.tree.map(lambda a, g: a + g, acc, grads)

        def do_update(op):
            p0, o0, a0 = op
            g = jax.tree.map(lambda a: a / k_steps, a0) if avg else a0
            # the optimizer's step count is the number of APPLIED updates
            # (Adam bias correction must see t=1,2,... — matching the eager
            # GradientMergeOptimizer, which steps the inner opt every k-th
            # call), not the micro-step counter
            np_, no_ = apply_fn(p0, g, o0, lr, step_i // k_steps)
            return np_, no_, jax.tree.map(jnp.zeros_like, a0)

        def keep(op):
            return op

        return jax.lax.cond(step_i % k_steps == 0, do_update, keep,
                            (params, opt_state, acc))

    return update


def init_grad_acc(params, k_steps):
    """Zero accumulator tree (empty when accumulation is off)."""
    if k_steps <= 1:
        return {}
    return {n: jnp.zeros_like(a) for n, a in params.items()}
