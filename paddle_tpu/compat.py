"""paddle.compat (ref python/paddle/compat.py) — py2/3 helpers the 1.x
API referenced; modern no-ops kept for import compatibility."""


def to_text(obj, encoding="utf-8"):
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return str(obj)


def to_bytes(obj, encoding="utf-8"):
    if isinstance(obj, str):
        return obj.encode(encoding)
    return bytes(obj)


def round(x, d=0):          # noqa: A001
    """Half-AWAY-FROM-ZERO rounding (the reference's compat.round exists
    precisely to avoid python3 banker's rounding)."""
    import math as _math
    scale = 10 ** d
    v = x * scale
    r = _math.floor(abs(v) + 0.5) * (1 if v >= 0 else -1)
    return r / scale


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
