"""Custom op extension: python/pallas ops + JIT-built C++ host kernels.

TPU-native analog of the reference custom-op plugin system
(ref paddle/fluid/extension/include/op_meta_info.h:360 PD_BUILD_OP,
framework/custom_operator.cc, python/paddle/utils/cpp_extension/ —
setuptools JIT build + dlopen registration):

- `register_op(name, forward, backward=None)`: the PD_BUILD_OP equivalent.
  forward is pure jnp/pallas code; backward (optional) installs a custom
  VJP. The op lands in the same registry/dispatch path as builtins, so it
  works eagerly, under tape autograd, and inside jit/shard_map.
- `load(name, sources, ...)`: builds a C++ source into a shared library
  with g++ (no torch/pybind needed — plain `extern "C"` symbols via
  ctypes), mirroring cpp_extension.load's JIT workflow. Device note: C++
  host kernels enter traced programs through `jax.pure_callback`
  (host-callback — the TPU equivalent of a CPU kernel registration;
  compute-critical custom kernels should be Pallas instead).
"""
import ctypes
import os
import subprocess
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.dispatch import def_op, OP_REGISTRY


def register_op(name, forward, backward=None, differentiable=True):
    """PD_BUILD_OP analog: register `forward(*arrays, **attrs)` as op `name`.

    backward(ctx_inputs, cotangents) -> input grads installs a custom VJP
    (ref op_meta_info SetKernelFn/SetBackwardFn)."""
    if backward is not None:
        fwd = jax.custom_vjp(forward)

        def f_fwd(*args):
            return forward(*args), args

        def f_bwd(res, g):
            out = backward(res, g)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        fwd.defvjp(f_fwd, f_bwd)
        fn = fwd
        fn.__name__ = name
    else:
        fn = forward
    return def_op(name, differentiable=differentiable)(fn)


def get_op(name):
    return OP_REGISTRY.get(name)


# --------------------------------------------------------------------------- #
# C++ JIT build (cpp_extension.load analog)                                   #
# --------------------------------------------------------------------------- #

_DEFAULT_FLAGS = ["-O3", "-fPIC", "-shared", "-std=c++17"]


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False):
    """Compile C++ `sources` into lib{name}.so and dlopen it (ref
    python/paddle/utils/cpp_extension/cpp_extension.py load). Returns the
    ctypes.CDLL; pair with `host_op` to expose an extern-C kernel as an op."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}.so")
    srcs = [sources] if isinstance(sources, str) else list(sources)
    cmd = ["g++"] + _DEFAULT_FLAGS + (extra_cxx_cflags or []) + \
        srcs + ["-o", out]
    if verbose:
        print("cpp_extension build:", " ".join(cmd))
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(
            f"cpp_extension build failed for {name}:\n{res.stderr}")
    return ctypes.CDLL(out)


def host_op(name, lib, symbol, out_like=None, differentiable=False):
    """Register extern-C `symbol(float* out, const float* in, int64 n)` from
    `lib` as op `name`, callable inside traced programs via pure_callback
    (the CPU-kernel path of custom_operator.cc re-homed to host callback)."""
    cfn = getattr(lib, symbol)
    cfn.restype = None
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    def host_call(x):
        x = np.ascontiguousarray(np.asarray(x, dtype=np.float32))
        out = np.empty_like(x)
        cfn(out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(x.size))
        return out

    def op(x):
        return jax.pure_callback(
            host_call, jax.ShapeDtypeStruct(x.shape, jnp.float32), x,
            vmap_method="sequential")

    op.__name__ = name
    return def_op(name, differentiable=differentiable)(op)
