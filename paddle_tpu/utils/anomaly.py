"""Online anomaly detection over the live metric plane, with a
journaled alert manager.

utils/timeseries.py banks what every metric just did; this module
judges it.  Two detector families:

* `RobustEWMA` — an exponentially-weighted mean + mean-absolute-
  deviation tracker with a z-score trigger and hysteresis.  It catches
  both spikes and step-changes: a level shift scores a large z the
  moment it lands (firing), then the EWMA absorbs the new level and the
  z decays back under the clear threshold (cleared) — so a one-time
  regime change is exactly one firing/cleared pair, never a flood.
* rule detectors — closed-form checks that need no statistics:
  recompile-after-warmup (`xla_compiles_total` delta on a labeled
  function), prefix-cache hit-rate collapse (windowed hit rate against
  its own EWMA baseline), and fleet replica queue-skew imbalance.

An `AlertRule` names one check; the `AlertManager` runs the set and
latches per-rule state with the same transition discipline as the SLO
engine (serving/slo.py): state changes bump `alerts_fired_total{rule}`,
move `alerts_active{rule}`, and journal an `alert` flight-recorder
event — steady state journals nothing.  `health()` merges into
/healthz and `FleetRouter.health()`; `summary()` is the rollup
bench.py / bench_serving.py embed in their BENCH JSON.

Every `AlertRule` id constructed in code must be documented in the
alert table of docs/observability.md — the `alert-rule-documented`
ptlint rule enforces it, same contract as metric names.
"""

import math
import sys
import threading

from . import flight_recorder, telemetry

_FIRED = telemetry.counter(
    "alerts_fired_total",
    "Alert firing transitions per rule (cleared->firing edges only; "
    "steady-state breach does not re-count)", labelnames=("rule",))
_ACTIVE = telemetry.gauge(
    "alerts_active",
    "1 while the rule's alert is firing, 0 otherwise",
    labelnames=("rule",))


class RobustEWMA:
    """Robust online z-score with hysteresis.

    Tracks an EWMA of the value and of its absolute deviation (a
    robust scale proxy — one outlier moves it by alpha, not
    quadratically).  `update(x)` scores x against the *pre-update*
    statistics, then folds x in, so a spike cannot mask itself; because
    the statistics keep adapting while firing, a sustained level shift
    clears on its own once the baseline catches up.

    `direction` gates which side of the baseline can FIRE: "up" (only
    x above the mean — latency/queue/utilization alerts), "down" (only
    x below — acceptance-rate alerts), "both".  One-sided rules do not
    re-fire on the recovery edge: latency falling back to normal is the
    resolution, not a second anomaly.  Clearing is always two-sided."""

    def __init__(self, alpha=0.25, z_fire=4.0, z_clear=1.25, warmup=8,
                 min_delta=0.0, rel_floor=0.05, abs_floor=1e-9,
                 direction="both"):
        if direction not in ("up", "down", "both"):
            raise ValueError(f"direction {direction!r} not in "
                             f"('up', 'down', 'both')")
        self.alpha = float(alpha)
        self.z_fire = float(z_fire)
        self.z_clear = float(z_clear)
        self.warmup = int(warmup)
        self.min_delta = float(min_delta)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.direction = direction
        self.mean = None
        self.mad = 0.0
        self.n = 0
        self.firing = False
        self.last_z = 0.0

    def update(self, x):
        x = float(x)
        if not math.isfinite(x):
            return self.firing
        if self.mean is None:
            self.mean, self.n = x, 1
            return False
        dev = abs(x - self.mean)
        scale = max(self.mad, self.rel_floor * abs(self.mean),
                    self.abs_floor)
        z = dev / scale
        self.last_z = z
        side_ok = (self.direction == "both"
                   or (self.direction == "up" and x > self.mean)
                   or (self.direction == "down" and x < self.mean))
        if self.firing:
            if z < self.z_clear:
                self.firing = False
        elif (side_ok and self.n >= self.warmup and z >= self.z_fire
              and dev > self.min_delta):
            self.firing = True
        self.mean += self.alpha * (x - self.mean)
        self.mad += self.alpha * (dev - self.mad)
        self.n += 1
        return self.firing


class AlertRule:
    """One named check.  `check(ctx)` returns None (not evaluable this
    round — missing metric, warming up) or a dict with at least
    `firing: bool`; extra keys (value, z, function, ...) ride the
    journal event as detail.  The id must appear in the
    docs/observability.md alert table (ptlint `alert-rule-documented`)."""

    def __init__(self, rule_id, check, description="",
                 severity="warning"):
        self.id = str(rule_id)
        self.check = check
        self.description = str(description)
        self.severity = str(severity)


# ---------------------------------------------------------------------------
# value sources (read-only registry probes — never create a series)
# ---------------------------------------------------------------------------

def _hist_pct(name, q):
    def read():
        m = telemetry.REGISTRY.get(name)
        if m is None or m.kind != "histogram":
            return None
        child = m.peek()
        if child is None or child.count() == 0:
            return None
        return child.percentile(q)
    return read


def _gauge_value(name):
    return lambda: telemetry.value(name)


# ---------------------------------------------------------------------------
# detector -> check adapters
# ---------------------------------------------------------------------------

def ewma_check(value_fn, detector=None, **detector_kw):
    """Wrap a value source + RobustEWMA into an AlertRule check."""
    det = detector or RobustEWMA(**detector_kw)

    def check(ctx):
        v = value_fn()
        if v is None:
            return None
        firing = det.update(v)
        return {"firing": firing, "value": float(v),
                "z": round(det.last_z, 3),
                "baseline": None if det.mean is None
                else round(det.mean, 6)}
    return check


def recompile_check(functions=None, ignore=("unattributed",)):
    """Fires when `xla_compiles_total{function=...}` moves AFTER that
    function's warmup compile was already seen — a recompile mid-stream,
    the silent latency cliff the fusion literature warns about.  Clears
    on the next evaluation with no new delta (a recompile is an event,
    not a state)."""
    watch = tuple(functions) if functions else None
    seen = {}

    def check(ctx):
        m = telemetry.REGISTRY.get("xla_compiles_total")
        if m is None:
            return None
        hot = []
        for label_values, child in m._series():
            fn = label_values[0] if label_values else ""
            if fn in ignore or (watch is not None and fn not in watch):
                continue
            count = child.value()
            prior = seen.get(fn)
            if prior is not None and prior >= 1 and count > prior:
                hot.append(fn)
            seen[fn] = count
        if hot:
            return {"firing": True, "functions": sorted(hot)}
        return {"firing": False}
    return check


def prefix_hit_collapse_check(min_events=8, fire_ratio=0.25,
                              clear_ratio=0.5, min_baseline=0.2,
                              alpha=0.25):
    """Windowed prefix-cache hit rate (delta of hits/misses since the
    last evaluation) collapsing against its own EWMA baseline: firing
    when the window's rate drops under `fire_ratio` x baseline, cleared
    back above `clear_ratio` x baseline.  Needs an established baseline
    (>= min_baseline) so a cache that never hit cannot 'collapse'."""
    state = {"hits": None, "misses": None, "ewma": None, "firing": False}

    def check(ctx):
        hits = telemetry.value("serving_prefix_cache_hits_total")
        misses = telemetry.value("serving_prefix_cache_misses_total")
        if hits is None or misses is None:
            return None
        if state["hits"] is None:
            state["hits"], state["misses"] = hits, misses
            return None
        dh, dm = hits - state["hits"], misses - state["misses"]
        state["hits"], state["misses"] = hits, misses
        if dh + dm < min_events:
            return {"firing": state["firing"]}
        rate = dh / (dh + dm)
        baseline = state["ewma"]
        if baseline is not None and baseline >= min_baseline:
            if state["firing"]:
                if rate >= clear_ratio * baseline:
                    state["firing"] = False
            elif rate < fire_ratio * baseline:
                state["firing"] = True
        # the baseline only absorbs non-firing windows: a collapse must
        # not drag its own reference down until it reads as normal
        if not state["firing"]:
            state["ewma"] = (rate if baseline is None
                             else baseline + alpha * (rate - baseline))
        return {"firing": state["firing"], "hit_rate": round(rate, 4),
                "baseline": None if state["ewma"] is None
                else round(state["ewma"], 4)}
    return check


def queue_skew_check(skew_fire=1.5, skew_clear=1.0, min_mean_depth=1.0,
                     consecutive=2):
    """Fleet replica queue imbalance: (max - min) / mean over the live
    replicas' queue depths (the router passes them in the evaluation
    context).  Fires after `consecutive` skewed rounds — one lopsided
    round during admission bursts is normal; a sustained skew means
    routing or a replica is sick."""
    state = {"streak": 0, "firing": False}

    def check(ctx):
        depths = (ctx or {}).get("replica_queue_depths")
        if not depths or len(depths) < 2:
            state["streak"] = 0
            if state["firing"]:
                state["firing"] = False
                return {"firing": False}
            return None
        vals = [float(v) for v in depths.values()]
        mean = sum(vals) / len(vals)
        if mean < min_mean_depth:
            state["streak"] = 0
            state["firing"] = False
            return {"firing": False, "mean_depth": round(mean, 3)}
        skew = (max(vals) - min(vals)) / mean
        if state["firing"]:
            if skew <= skew_clear:
                state["firing"] = False
                state["streak"] = 0
        elif skew >= skew_fire:
            state["streak"] += 1
            if state["streak"] >= consecutive:
                state["firing"] = True
        else:
            state["streak"] = 0
        return {"firing": state["firing"], "skew": round(skew, 3),
                "mean_depth": round(mean, 3)}
    return check


# ---------------------------------------------------------------------------
# default rule sets (ids literal at the AlertRule call, for the lint)
# ---------------------------------------------------------------------------

def default_serving_rules(detector_kw=None):
    """The serving-side detector set the scheduler evaluates once per
    working round.  `detector_kw` overrides RobustEWMA parameters for
    every statistical rule (tests tighten warmup there)."""
    # one-sided by default: a latency/queue/utilization alert is an
    # upper bound, acceptance rate a lower bound — the recovery edge
    # must not read as a second anomaly. detector_kw still wins.
    up = dict({"direction": "up"}, **(detector_kw or {}))
    down = dict({"direction": "down"}, **(detector_kw or {}))
    return [
        AlertRule("ttft_p99_anomaly",
                  ewma_check(_hist_pct("serving_ttft_seconds", 99), **up),
                  "step-change/spike in p99 time-to-first-token"),
        AlertRule("tpot_p99_anomaly",
                  ewma_check(_hist_pct("serving_tpot_seconds", 99), **up),
                  "step-change/spike in p99 inter-token latency"),
        AlertRule("queue_depth_anomaly",
                  ewma_check(_gauge_value("serving_queue_depth"), **up),
                  "queue depth step-change (admission outrunning decode)"),
        AlertRule("hbm_util_anomaly",
                  ewma_check(_gauge_value("serving_hbm_util"), **up),
                  "HBM-roofline utilization shifted regime mid-stream"),
        AlertRule("spec_acceptance_anomaly",
                  ewma_check(
                      _gauge_value("serving_spec_acceptance_rate"),
                      **down),
                  "speculative acceptance rate drifted (draft quality)"),
        AlertRule("recompile_after_warmup", recompile_check(),
                  "a warmed compiled function compiled AGAIN mid-stream",
                  severity="critical"),
        AlertRule("prefix_hit_collapse", prefix_hit_collapse_check(),
                  "prefix-cache hit rate collapsed vs its own baseline"),
    ]


def default_train_rules(detector_kw=None):
    """Training-side set (hapi TelemetryCallback evaluates per step)."""
    up = dict({"direction": "up"}, **(detector_kw or {}))
    return [
        AlertRule("train_step_time_anomaly",
                  ewma_check(_hist_pct("train_step_seconds", 99), **up),
                  "p99 train-step wall time step-change"),
        AlertRule("recompile_after_warmup", recompile_check(),
                  "a warmed compiled function compiled AGAIN mid-run",
                  severity="critical"),
    ]


def default_fleet_rules(detector_kw=None):
    """Router-side set: serving rules plus the cross-replica skew check
    (only the router knows per-replica depths)."""
    return default_serving_rules(detector_kw) + [
        AlertRule("fleet_queue_skew", queue_skew_check(),
                  "sustained queue-depth imbalance across fleet replicas"),
    ]


class AlertManager:
    """Runs an AlertRule set and latches firing/cleared per rule.

    Same transition discipline as the SLO engine's burn-rate latch: a
    state CHANGE bumps `alerts_fired_total{rule}`, flips
    `alerts_active{rule}`, and journals ONE `alert` event through the
    current flight recorder; a steady breach (or steady calm) does
    nothing.  A raising detector is contained and counted — observers
    must never take the serving loop down."""

    def __init__(self, rules=None, recorder=None):
        self.rules = list(rules) if rules is not None \
            else default_serving_rules()
        self._recorder = recorder
        self._lock = threading.Lock()
        self._state = {}
        self.check_errors = 0
        #: path of the most recent incident bundle snapped by a firing
        #: transition (None until a black-box recorder with a bundle_dir
        #: is attached and a rule latches)
        self.last_bundle = None
        for rule in self.rules:
            self._state[rule.id] = {"active": False, "fired": 0,
                                    "cleared": 0, "last": None}
            _ACTIVE.labels(rule=rule.id).set(0.0)

    def evaluate(self, context=None):
        """One detection round over every rule.  Returns the transitions
        it journaled as (rule_id, "firing"|"cleared") pairs."""
        ctx = context or {}
        transitions = []
        with self._lock:
            for rule in self.rules:
                try:
                    res = rule.check(ctx)
                except Exception:   # noqa: BLE001 — observer, not actor
                    self.check_errors += 1
                    continue
                if res is None:
                    continue
                st = self._state[rule.id]
                st["last"] = res
                firing = bool(res.get("firing"))
                if firing == st["active"]:
                    continue
                st["active"] = firing
                action = "firing" if firing else "cleared"
                st["fired" if firing else "cleared"] += 1
                if firing:
                    _FIRED.labels(rule=rule.id).inc()
                _ACTIVE.labels(rule=rule.id).set(1.0 if firing else 0.0)
                detail = {k: v for k, v in res.items() if k != "firing"}
                if firing:
                    bundle = self._snapshot_incident(rule, detail)
                    if bundle is not None:
                        detail["bundle"] = bundle
                        self.last_bundle = bundle
                rec = self._recorder or flight_recorder.get_recorder()
                if rec is not None:
                    rec.alert(rule=rule.id, action=action,
                              severity=rule.severity, **detail)
                transitions.append((rule.id, action))
        return transitions

    def _snapshot_incident(self, rule, detail):
        """Freeze a self-contained incident bundle through the serving
        black-box recorder, if one is attached with a bundle_dir.
        Resolved through sys.modules, not an import: utils must not
        depend on serving, and a recorder can only exist if the blackbox
        module was already imported by whoever installed it."""
        bb_mod = sys.modules.get("paddle_tpu.serving.blackbox")
        if bb_mod is None:
            return None
        try:
            bb = bb_mod.get_recorder()
            if bb is None or bb.bundle_dir is None:
                return None
            return bb.incident_bundle(rule=rule.id,
                                      severity=rule.severity,
                                      detail=dict(detail))
        except Exception:   # noqa: BLE001 — observer, not actor
            self.check_errors += 1
            return None

    # ------------------------------------------------------------- readers
    def active(self):
        with self._lock:
            return sorted(r for r, st in self._state.items()
                          if st["active"])

    def counts(self, rule_id):
        with self._lock:
            st = self._state[rule_id]
            return {"fired": st["fired"], "cleared": st["cleared"],
                    "active": st["active"]}

    def summary(self):
        """Per-rule fired/cleared rollup (the BENCH JSON embed)."""
        with self._lock:
            rules = {r: {"fired": st["fired"], "cleared": st["cleared"],
                         "active": st["active"]}
                     for r, st in sorted(self._state.items())}
            return {
                "rules": rules,
                "fired_total": sum(s["fired"] for s in rules.values()),
                "active": sorted(r for r, s in rules.items()
                                 if s["active"]),
                "check_errors": self.check_errors,
            }

    def health(self):
        """The /healthz + FleetRouter.health() merge fragment."""
        s = self.summary()
        return {"alerts": {"active": s["active"],
                           "fired_total": s["fired_total"]}}
