"""Deterministic fault injection (chaos) harness.

The resilience layer (serving fault isolation, wave retry, admission
control, crash-safe checkpoints) is only trustworthy if every recovery
path is *provoked* on demand — the same positive-control discipline the
static gates use (`hlo_audit --inject`, `jxaudit --inject`). This module
is the injector: named, seeded, scoped fault points that production
code consults and test harnesses arm.

    from paddle_tpu.utils import chaos

    monkey = chaos.ChaosMonkey([
        chaos.Fault(chaos.DECODE_WAVE, action="raise", times=(2,)),
        chaos.Fault(chaos.DECODE_WAVE_NAN, action="payload",
                    payload=1, times=(3,)),
    ], seed=0)
    with chaos.active(monkey):
        ...drive the serving engine...

Contract with production call sites (enforced by ptlint's `chaos-guard`
rule, docs/static_analysis.md):

  * every call to `chaos.fire(...)` / `chaos.value(...)` outside this
    module is lexically guarded by `if chaos.enabled():` — with no
    monkey installed the fault point costs one module-global read and
    nothing else (zero-cost when disabled);
  * call sites import the MODULE (`from ..utils import chaos`), never
    the functions, so the guard stays visible at the point of use;
  * fault points are the named constants below — scoped, greppable,
    and stable for Fault(point=...) selectors.

Selection is deterministic: each point keeps a per-monkey invocation
counter and a fault fires on exact 1-based invocation indices
(`times`), a modulus (`every`), or a seeded Bernoulli draw (`prob`,
`random.Random(seed)` — reproducible across runs). Every firing is
journaled as a `chaos` event through the current flight recorder, so a
recovered run's journal shows the injection next to the `fault` events
the resilience layer wrote while handling it.
"""
import contextlib
import random
import threading
import time

from . import flight_recorder

# ---------------------------------------------------------------------------
# fault point names (the scoped vocabulary — see docs/serving.md)
# ---------------------------------------------------------------------------

#: raise/delay before the batched decode wave dispatches (host-side, so
#: no state mutated and no donated buffer consumed — retry is safe)
DECODE_WAVE = "serving.decode_wave"
#: payload: slot index (or list of indices) whose logits are poisoned
#: to NaN THIS wave via the program's poison input — exercises the
#: fused non-finite sentinel without a recompile
DECODE_WAVE_NAN = "serving.decode_wave.nan"
#: raise/delay before a prefill admission dispatches
PREFILL = "serving.prefill"
#: raise inside the per-request on_token callback guard
CALLBACK = "serving.request.callback"
#: raise mid-write inside the atomic checkpoint writer (partial temp
#: file on disk, destination untouched — simulates a crash)
CHECKPOINT_WRITE = "serialization.save"
#: payload (truthy): the paged KV BlockPool reports exhaustion for this
#: alloc() call even though free blocks remain — exercises the
#: shed/queue/preempt paths without needing a pool actually sized to
#: starve (a raise-type fault here instead simulates the allocator
#: CRASHING, which must surface as a request-isolated error)
CACHE_ALLOC = "serving.cache_alloc"
#: raise/delay at the train-step boundary BEFORE the compiled step
#: dispatches (host-side: params/opt-state/grad-acc untouched, the RNG
#: chain not yet advanced) — a raise here is the canonical "kill" the
#: exact-resume parity harness (scripts/chaos_train.py) injects, and a
#: delay is a stalled step for the training watchdog to catch
TRAIN_STEP = "train.step"
#: raise/delay around the train loop's next(batch) — a crashing or
#: stalled input pipeline (Model.fit's _timed_iter consults it, so the
#: firing carries the batch index the cursor would record)
DATA_LOAD = "train.data_load"
#: raise before an EAGER collective op dispatches — exercises the
#: timeout/retry wrapper in distributed/collective.py (traced call
#: sites never consult it: a trace-time raise would poison the
#: executable, not simulate a transient transport error)
COLLECTIVE = "distributed.collective"
#: payload: iterable of train-state keys DROPPED from the checkpoint's
#: captured state (utils/resume.capture_train_state) — the resume
#: parity harness's positive controls arm this ("rng" dropped must
#: make the kill/resume parity check fail)
TRAIN_STATE = "resume.capture"
#: payload: param-name fragment (or True = first param) whose gathered
#: optimizer-state host copies are ZEROED during ShardedTrainStep.sync
#: — simulates a shard gather that missed the dp shards' updates; the
#: sharded kill/resume parity harness's `--inject stale-shard` positive
#: control arms this (the resumed trajectory must diverge, exit 1)
SHARD_STATE = "sharded.state_gather"
#: payload: rotation index of a fleet replica to KILL before this fleet
#: step (serving/fleet router loop) — the replica is marked dead, its
#: accepted requests are evacuated and must finish token-identically on
#: a surviving replica (scripts/chaos_serving.py replica_failover)
REPLICA_KILL = "fleet.replica_kill"
#: raise/delay before the router hands a request to its chosen
#: replica's scheduler — a dispatch crash must reroute to the next
#: candidate, never lose the accepted request
ROUTER_DISPATCH = "fleet.router_dispatch"
#: payload (truthy): the block-level KV handoff import sees a CORRUPT
#: payload — its digest check must refuse the transfer (the request
#: fails request-isolated, never silently decodes over corrupt K/V);
#: scripts/chaos_serving.py prefill_handoff_kill's `--inject
#: corrupt-handoff` positive control arms this
HANDOFF_IMPORT = "fleet.handoff_import"

POINTS = (DECODE_WAVE, DECODE_WAVE_NAN, PREFILL, CALLBACK,
          CHECKPOINT_WRITE, CACHE_ALLOC, TRAIN_STEP, DATA_LOAD,
          COLLECTIVE, TRAIN_STATE, SHARD_STATE, REPLICA_KILL,
          ROUTER_DISPATCH, HANDOFF_IMPORT)

ACTIONS = ("raise", "delay", "payload")


class ChaosError(RuntimeError):
    """An injected fault (the 'transient device error' stand-in). The
    resilience layer must treat it exactly like any other exception —
    nothing may special-case this type."""


class Fault:
    """One armed fault: a point, an action, and a deterministic
    selector.

    point: one of the named fault points above (any string is accepted
        — harnesses may define private points).
    action: "raise" (ChaosError), "delay" (time.sleep(delay_s)), or
        "payload" (fire() returns `payload` to the call site).
    times: 1-based invocation indices of `point` at which to fire.
    every: fire when the invocation index is a multiple of this.
    prob: fire on a seeded Bernoulli draw per invocation.
        With no selector at all, every invocation fires.
    max_fires: cap on total firings (None = unbounded).
    """

    def __init__(self, point, action="raise", times=None, every=None,
                 prob=None, payload=None, delay_s=0.0, max_fires=None,
                 message=None):
        if action not in ACTIONS:
            raise ValueError(f"action must be one of {ACTIONS}, "
                             f"got {action!r}")
        if action == "delay" and delay_s <= 0:
            raise ValueError("delay fault needs delay_s > 0")
        self.point = str(point)
        self.action = action
        self.times = None if times is None else tuple(int(t) for t in times)
        self.every = None if every is None else int(every)
        if self.every is not None and self.every <= 0:
            # fail at construction, not as a ZeroDivisionError out of
            # the production fault point mid-wave
            raise ValueError(f"every must be >= 1, got {every!r}")
        self.prob = None if prob is None else float(prob)
        self.payload = payload
        self.delay_s = float(delay_s)
        self.max_fires = max_fires
        self.message = message or f"injected fault at {self.point}"
        self.fires = 0

    def should_fire(self, invocation, rng):
        """Caller holds the monkey's lock."""
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.times is not None:
            return invocation in self.times
        if self.every is not None:
            return invocation % self.every == 0
        if self.prob is not None:
            return rng.random() < self.prob
        return True

    def __repr__(self):
        sel = (f"times={self.times}" if self.times is not None else
               f"every={self.every}" if self.every is not None else
               f"prob={self.prob}" if self.prob is not None else "always")
        return f"Fault({self.point!r}, {self.action}, {sel})"


class ChaosMonkey:
    """A set of armed faults plus the deterministic firing state: one
    invocation counter per point and one seeded RNG shared by every
    `prob` selector. `fired` records (point, action, invocation) for
    post-run assertions."""

    def __init__(self, faults, seed=0):
        self.faults = list(faults)
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._invocations = {}
        self.fired = []

    def match(self, point):
        """Count one invocation of `point`; return (fault, invocation)
        with fault=None when nothing fires this time."""
        with self._lock:
            n = self._invocations.get(point, 0) + 1
            self._invocations[point] = n
            for fault in self.faults:
                if fault.point == point and fault.should_fire(n, self.rng):
                    fault.fires += 1
                    self.fired.append((point, fault.action, n))
                    return fault, n
        return None, n

    def invocations(self, point):
        with self._lock:
            return self._invocations.get(point, 0)


# ---------------------------------------------------------------------------
# module state: the installed monkey
# ---------------------------------------------------------------------------

_install_lock = threading.Lock()
_monkey = None


def install(monkey):
    """Install `monkey` as the process-wide injector; returns the
    previous one. Pass None to disarm."""
    global _monkey
    with _install_lock:
        prev = _monkey
        _monkey = monkey
        return prev


def uninstall():
    return install(None)


def enabled():
    """True when a monkey is installed — THE guard every production
    fault point checks before calling fire()/value()."""
    return _monkey is not None


def current():
    return _monkey


@contextlib.contextmanager
def active(monkey):
    """`with chaos.active(ChaosMonkey([...])):` — scoped arm/disarm."""
    prev = install(monkey)
    try:
        yield monkey
    finally:
        install(prev)


def fire(point, **ctx):
    """Consult the installed monkey at a fault point. Returns None when
    nothing fires; raises ChaosError / sleeps / returns the payload when
    a fault matches. `ctx` kwargs are journaled with the firing."""
    monkey = _monkey
    if monkey is None:
        return None
    fault, n = monkey.match(point)
    if fault is None:
        return None
    rec = flight_recorder.get_recorder()
    if rec is not None:
        rec.chaos(point=point, action=fault.action, invocation=n, **ctx)
    if fault.action == "delay":
        time.sleep(fault.delay_s)
        return None
    if fault.action == "payload":
        return fault.payload
    raise ChaosError(f"chaos[{point}#{n}]: {fault.message}")


def value(point, default=None, **ctx):
    """Payload-point sugar: the injected payload when a fault fires,
    `default` otherwise (raise/delay faults behave as in fire())."""
    out = fire(point, **ctx)
    return default if out is None else out
