"""Profiler: host event recorder + XLA/TPU device trace bridge.

TPU-native redesign of the reference profiler
(ref paddle/fluid/platform/profiler.h:127,210 RecordEvent /
EnableProfiler/DisableProfiler, device_tracer.cc CUPTI bridge,
tools/timeline.py chrome-trace writer): host-side RAII events aggregate into
the same kind of per-op summary table; the device side delegates to
`jax.profiler` (XPlane), whose traces open in TensorBoard/Perfetto — the
CUPTI-equivalent on TPU. `export_chrome_tracing` keeps the
chrome://tracing workflow of tools/timeline.py.
"""
import contextlib
import json
import threading
import time

_lock = threading.Lock()
_enabled = False
_events = []          # (name, start_s, dur_s, thread_id, pid)
_raw_events = []      # chrome-format dicts (async spans, flow, counters)
_trace_gen = 0        # bumped when _raw_events is cleared (new trace)
_active_trace_dir = None


def trace_generation():
    """Monotone id of the current trace buffer. Emitters holding
    open-span/flow state across traces (telemetry.trace_request) compare
    it so a request straddling a profiler restart doesn't emit
    span-ends/flow-finishes whose partners died with the old buffer."""
    return _trace_gen


def now_us():
    """Microsecond timestamp on the SAME clock the host events use —
    raw trace events must share it or spans drift off the timeline."""
    return time.perf_counter() * 1e6


def trace_enabled():
    return _enabled


def emit_trace_event(event):
    """Append one raw chrome-trace event (async 'b'/'n'/'e', flow
    's'/'t'/'f', counter 'C', instant 'i', ...) to the host trace.
    Fills ts/pid/tid defaults; dropped (returns False) when the profiler
    is not recording — callers can emit unconditionally."""
    if not _enabled:
        return False
    ev = dict(event)
    ev.setdefault("ts", now_us())
    ev.setdefault("pid", 0)
    ev.setdefault("tid", threading.get_ident() % 10000)
    with _lock:
        _raw_events.append(ev)
    return True


class RecordEvent:
    """RAII host event (ref platform/profiler.h:127). Usable as context
    manager or decorator; nesting is recorded flat like the reference.

    `pid` places the slice on a chrome-trace process row (the fleet
    router exports each replica's scheduler activity on its own row —
    pid = replica_id + 1, pid 0 is the router/host). `elapsed` holds
    the measured duration in seconds after exit whether or not the
    profiler was recording, so callers can both trace AND meter one
    timed region (the scheduler's per-phase attribution)."""

    def __init__(self, name, pid=0):
        self.name = name
        self.pid = int(pid)
        self.elapsed = None
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            self.elapsed = time.perf_counter() - self._t0
            if _enabled:
                with _lock:
                    _events.append((self.name, self._t0, self.elapsed,
                                    threading.get_ident(), self.pid))
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapped


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    """ref EnableProfiler (profiler.h:210). When `trace_dir` is given, also
    start a jax.profiler device trace (XPlane -> TensorBoard)."""
    global _enabled, _active_trace_dir, _trace_gen
    with _lock:
        _events.clear()
        _raw_events.clear()
        _trace_gen += 1
        _enabled = True
    if trace_dir is not None:
        import jax
        jax.profiler.start_trace(trace_dir)
        with _lock:
            _active_trace_dir = trace_dir


def stop_profiler(sorted_key="total", profile_path=None):
    """ref DisableProfiler. Prints the aggregated per-event table; writes a
    chrome trace json when profile_path is given (tools/timeline.py analog)."""
    global _enabled, _active_trace_dir
    with _lock:
        _enabled = False
    if _active_trace_dir is not None:
        import jax
        jax.profiler.stop_trace()
        with _lock:
            _active_trace_dir = None
    stats = summary(sorted_key)
    if profile_path:
        export_chrome_tracing(profile_path)
    return stats


def summary(sorted_key="total"):
    """Aggregate events -> list of dicts (name, calls, total_ms, avg_ms,
    min_ms, max_ms), printed like the reference profiler table."""
    agg = {}
    with _lock:
        evs = list(_events)
    for name, _t0, dur, _tid, _pid in evs:
        a = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        a[0] += 1
        a[1] += dur
        a[2] = min(a[2], dur)
        a[3] = max(a[3], dur)
    rows = [{"name": n, "calls": c, "total_ms": t * 1e3,
             "avg_ms": t * 1e3 / c, "min_ms": lo * 1e3, "max_ms": hi * 1e3}
            for n, (c, t, lo, hi) in agg.items()]
    key = {"total": "total_ms", "calls": "calls", "max": "max_ms",
           "min": "min_ms", "ave": "avg_ms"}.get(sorted_key, "total_ms")
    rows.sort(key=lambda r: r[key], reverse=True)
    if rows:
        w = max(len(r["name"]) for r in rows)
        print(f"{'Event':<{w}}  Calls  Total(ms)  Avg(ms)  Min(ms)  Max(ms)")
        for r in rows:
            print(f"{r['name']:<{w}}  {r['calls']:>5}  {r['total_ms']:>9.3f}"
                  f"  {r['avg_ms']:>7.3f}  {r['min_ms']:>7.3f}"
                  f"  {r['max_ms']:>7.3f}")
    return rows


def export_chrome_tracing(path, extra_events=()):
    """Write host events as chrome://tracing json (tools/timeline.py).
    RecordEvent slices ('X') merge with the raw events other layers emit
    through emit_trace_event (serving request spans/flows, counters) so
    one trace shows host events, decode waves, and request lifecycles.
    `extra_events` are appended verbatim — the fleet router passes 'M'
    process_name metadata naming each replica's pid row when it merges
    the per-replica sinks into one trace."""
    with _lock:
        evs = list(_events)
        raw = [dict(e) for e in _raw_events]
    events = [
        {"name": name, "ph": "X", "ts": t0 * 1e6, "dur": dur * 1e6,
         "pid": pid, "tid": tid % 10000, "cat": "host"}
        for name, t0, dur, tid, pid in evs]
    trace = {"traceEvents": events + raw + [dict(e)
                                            for e in extra_events]}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             trace_dir=None):
    """with profiler(): ... — start/stop convenience
    (ref python/paddle/fluid/profiler.py profiler ctx)."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# --------------------------------------------------------------------------
# paddle.profiler new-style API (ref python/paddle/profiler/profiler.py:
# Profiler(targets, scheduler, on_trace_ready) + make_scheduler)
# --------------------------------------------------------------------------

class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"          # accepted alias: the device side is the TPU trace
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """ref profiler.make_scheduler: step-state machine. Returns
    fn(step) -> 'closed'|'ready'|'record' (repeat=0 means cycle forever;
    a zero-length cycle — closed=ready=record=0 — never records)."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first or cycle == 0:
            return "closed"
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return "closed"
        pos = s % cycle
        if pos < closed:
            return "closed"
        if pos < closed + ready:
            return "ready"
        return "record"

    return schedule


class Profiler:
    """ref python/paddle/profiler/profiler.py Profiler: step-scheduled
    host + device tracing.

        p = profiler.Profiler(trace_dir="/tmp/trace",
                              scheduler=make_scheduler(closed=1, ready=1,
                                                       record=3))
        p.start()
        for batch in loader:
            train_step(batch)
            p.step()
        p.stop()                 # host table + XPlane dump for TensorBoard
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir=None, timer_only=False):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self.scheduler = scheduler or (lambda step: "record")
        self.on_trace_ready = on_trace_ready
        self.trace_dir = trace_dir
        self.timer_only = timer_only
        self._step = 0
        self._recording = False
        self._device_active = False

    def start(self):
        self._apply_state(self.scheduler(self._step))
        return self

    def step(self):
        self._step += 1
        self._apply_state(self.scheduler(self._step))

    def _apply_state(self, st):
        global _enabled
        want_record = st == "record"
        if want_record and not self._recording:
            with _lock:
                _enabled = True
            self._recording = True
            # `a and b and c or d` bug fixed here: the un-parenthesized
            # form started a DEVICE trace whenever GPU was in targets,
            # even with timer_only=True or no trace_dir
            want_device = (self.trace_dir is not None
                           and not self.timer_only
                           and (ProfilerTarget.TPU in self.targets
                                or ProfilerTarget.GPU in self.targets))
            if want_device and not self._device_active:
                import jax
                jax.profiler.start_trace(self.trace_dir)
                self._device_active = True
        elif not want_record and self._recording:
            self._flush()

    def _flush(self):
        global _enabled
        with _lock:
            _enabled = False
        self._recording = False
        if self._device_active:
            import jax
            jax.profiler.stop_trace()
            self._device_active = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def stop(self):
        if self._recording:
            self._flush()

    def summary(self, sorted_by="total"):
        return summary(sorted_by)

    def export(self, path, format="json"):
        return export_chrome_tracing(path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def export_protobuf(path):
    """XPlane protobufs are written by jax.profiler into trace_dir; this
    helper names the convention for API parity (ref profiler export)."""
    return path
