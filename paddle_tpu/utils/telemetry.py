"""Unified telemetry: typed metric registry, Prometheus/JSON exporters,
request-trace emission, and XLA compile-event tracking.

This is the observability layer the reference stack spreads over
platform/monitor.h (StatRegistry), platform/profiler.h (RecordEvent) and
tools/timeline.py, rebuilt as one subsystem:

  * a typed metric REGISTRY — Counter / Gauge / Histogram with label
    sets and exponential latency buckets — that subsumes the flat
    `utils.monitor` int stats (they ride along in every snapshot and
    exposition) and renders both a JSON snapshot and the Prometheus
    text format;
  * an optional stdlib-`http.server` background thread (`MetricsServer`)
    exposing `/metrics` (Prometheus), `/metrics.json` (snapshot),
    `/healthz`, `/metrics/history` (the utils/timeseries ring-buffer
    history, `snapshot_history()`) and `/dashboard` (self-contained
    sparkline page);
  * XLA compile-event tracking: a `jax.monitoring` duration-listener
    counts backend compilations (persistent-cache loads included — a new
    executable entered this process either way) attributed to the
    function label on the `track_compiles` thread-local stack, so the
    serving engine's compile-once invariant is a live metric.  On jax
    builds without `jax.monitoring`, `instrument_jit` falls back to
    counting `_cache_size()` growth around each call (the wrap-jit
    fallback for old containers);
  * `trace_request`: chrome-trace async spans + flow events for the
    serving Request lifecycle (QUEUED → PREFILL → DECODE → DONE) emitted
    into `utils.profiler`'s event sink, so one exported trace shows host
    RecordEvents, decode waves, and per-request lifecycles together.

Metric names and label conventions are cataloged in
docs/observability.md; the `metric-name` rule of scripts/ptlint.py
lints call sites against that catalog.
"""
import bisect
import contextlib
import http.server
import io
import json
import re
import threading
import time

from . import monitor, profiler

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _check_name(name):
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"metric name must be snake_case ([a-z][a-z0-9_]*), got {name!r}")
    return name


def exponential_buckets(start=0.001, factor=2.0, count=16):
    """Exponential bucket upper bounds: start, start*factor, ... — the
    default (1ms..~32.8s) covers TTFT/step-time latencies without keeping
    raw samples."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


DEFAULT_LATENCY_BUCKETS = exponential_buckets()


# ---------------------------------------------------------------------------
# metric types
# ---------------------------------------------------------------------------

class _CounterChild:
    __slots__ = ("_lock", "_v")

    def __init__(self, lock):
        self._lock = lock
        self._v = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._lock:
            self._v += amount

    def value(self):
        with self._lock:
            return self._v

    def _reset(self):
        self._v = 0.0           # caller holds the lock


class _GaugeChild:
    __slots__ = ("_lock", "_v")

    def __init__(self, lock):
        self._lock = lock
        self._v = 0.0

    def set(self, value):
        with self._lock:
            self._v = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._v += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    def set_max(self, value):
        """Atomic running max — the peak-gauge idiom monitor.stat_max has."""
        with self._lock:
            self._v = max(self._v, float(value))

    def value(self):
        with self._lock:
            return self._v

    def _reset(self):
        self._v = 0.0


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count",
                 "_min", "_max")

    def __init__(self, lock, bounds):
        self._lock = lock
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)     # +Inf overflow last
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None

    def observe(self, value):
        v = float(value)
        if v != v or v in (float("inf"), float("-inf")):
            return     # a non-finite sample would poison sum/min/max and
                       # every percentile forever; drop it at the door
        idx = bisect.bisect_left(self._bounds, v)  # le: v == bound stays in
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    def count(self):
        with self._lock:
            return self._count

    def sum(self):
        with self._lock:
            return self._sum

    def bucket_counts(self):
        """[(upper_bound, cumulative_count), ..., (None, total)] — the
        Prometheus cumulative view; None stands for +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for ub, c in zip(self._bounds, counts):
            cum += c
            out.append((ub, cum))
        out.append((None, cum + counts[-1]))
        return out

    def percentile(self, q):
        """Estimate the q-th percentile from the buckets (linear
        interpolation within the bucket, clamped to the observed
        [min, max]); None when empty. The whole point of the rebase from
        raw sample lists: O(buckets) memory at any request count."""
        with self._lock:
            counts = list(self._counts)
            total, mn, mx = self._count, self._min, self._max
        if not total:
            return None
        target = (q / 100.0) * total
        cum, lower = 0.0, None
        for i, ub in enumerate(list(self._bounds) + [None]):
            c = counts[i]
            if c and cum + c >= target:
                lo = mn if lower is None else max(lower, mn)
                hi = mx if ub is None else min(ub, mx)
                if hi < lo:
                    hi = lo
                frac = (target - cum) / c
                return min(max(lo + frac * (hi - lo), mn), mx)
            cum += c
            lower = ub
        return mx

    def _reset(self):
        self._counts = [0] * (len(self._bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None


class _Metric:
    kind = "untyped"
    _child_args = ()

    def __init__(self, name, help="", labelnames=()):
        _check_name(name)
        self.name = name
        self.help = help
        self.labelnames = tuple(_check_name(n) for n in labelnames)
        self._lock = threading.Lock()
        self._children = {}

    def _normalize(self, values, kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "name, not both")
            extra = set(kv) - set(self.labelnames)
            if extra:
                raise ValueError(f"{self.name}: unexpected labels {extra}")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(f"{self.name} takes labels {self.labelnames}, "
                             f"got {values!r}")
        return values

    def labels(self, *values, **kv):
        """Bind label values -> child handle (created on first use)."""
        values = self._normalize(values, kv)
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._new_child()
                self._children[values] = child
        return child

    def peek(self, *values, **kv):
        """Non-creating lookup: the child for these label values, or
        None if that series has never been recorded. Read paths use this
        so a dashboard probe cannot mint permanent zero-valued series."""
        values = self._normalize(values, kv)
        with self._lock:
            return self._children.get(values)

    def _new_child(self):
        raise NotImplementedError

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; "
                             "use .labels(...)")
        return self.labels()

    def _series(self):
        with self._lock:
            return sorted(self._children.items())

    def _reset(self):
        with self._lock:
            for child in self._children.values():
                child._reset()


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    def value(self):
        return self._default().value()


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)

    def set(self, value):
        self._default().set(value)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    def dec(self, amount=1.0):
        self._default().dec(amount)

    def set_max(self, value):
        self._default().set_max(value)

    def value(self):
        return self._default().value()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"buckets must be distinct and increasing, "
                             f"got {buckets!r}")
        self.buckets = bounds

    def _new_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value):
        self._default().observe(value)

    def count(self):
        return self._default().count()

    def sum(self):
        return self._default().sum()

    def percentile(self, q):
        return self._default().percentile(q)

    def bucket_counts(self):
        return self._default().bucket_counts()


# ---------------------------------------------------------------------------
# registry + exporters
# ---------------------------------------------------------------------------

def _fmt(v):
    # non-finite values are legal Prometheus samples (a diverged
    # train_loss gauge is NaN) — render them instead of crashing /metrics
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.10g}"


def _json_safe(v):
    """JSON has no NaN/Inf literal (json.dumps would emit invalid JSON);
    snapshot consumers get the string spelling instead."""
    if v != v or v in (float("inf"), float("-inf")):
        return _fmt(v)
    return v


def _esc_label(v):
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _sample_line(name, labelnames, values, value, suffix="", extra=()):
    pairs = [f'{n}="{_esc_label(v)}"' for n, v in zip(labelnames, values)]
    pairs += [f'{n}="{_esc_label(v)}"' for n, v in extra]
    lbl = "{" + ",".join(pairs) + "}" if pairs else ""
    return f"{name}{suffix}{lbl} {_fmt(value)}"


class Registry:
    """Named metric registry. `counter`/`gauge`/`histogram` get-or-create
    (re-registration with the same kind+labels returns the existing
    metric — modules can declare their metrics at import time without
    ordering hazards); mismatched re-registration raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            cur = self._metrics.get(name)
            if cur is not None:
                if (type(cur) is not cls
                        or cur.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as {cur.kind} "
                        f"with labels {cur.labelnames}")
                want = kw.get("buckets")
                if want is not None and \
                        cur.buckets != tuple(float(b) for b in want):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {cur.buckets}, requested {tuple(want)}")
                return cur
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self):
        """Zero every series IN PLACE — registrations and any child
        handles modules cached stay live (tests isolate runs with this)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()

    # ------------------------------------------------------------- exporters
    def snapshot(self, include_monitor=True):
        """JSON-able point-in-time dump of every metric (and, by default,
        the flat utils.monitor stats alongside)."""
        out = {"time_unix": time.time(), "metrics": {}}
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            series = []
            for values, child in m._series():
                entry = {"labels": dict(zip(m.labelnames, values))}
                if m.kind == "histogram":
                    entry.update(
                        count=child.count(), sum=_json_safe(child.sum()),
                        buckets=[[ub, c]
                                 for ub, c in child.bucket_counts()])
                    p50 = child.percentile(50)
                    if p50 is not None:
                        entry["p50"] = p50
                        entry["p99"] = child.percentile(99)
                else:
                    entry["value"] = _json_safe(child.value())
                series.append(entry)
            out["metrics"][name] = {"kind": m.kind, "help": m.help,
                                    "labelnames": list(m.labelnames),
                                    "series": series}
        if include_monitor:
            out["monitor"] = monitor.all_stats()
        return out

    def render_prometheus(self, include_monitor=True):
        """Prometheus text exposition (format 0.0.4). Histograms render
        cumulative `_bucket{le=...}` + `_sum` + `_count`; the flat
        monitor stats ride along as untyped samples (names sanitized,
        typed metrics win collisions)."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} "
                             + m.help.replace("\\", "\\\\")
                                     .replace("\n", "\\n"))
            lines.append(f"# TYPE {name} {m.kind}")
            for values, child in m._series():
                if m.kind == "histogram":
                    for ub, cum in child.bucket_counts():
                        le = "+Inf" if ub is None else _fmt(ub)
                        lines.append(_sample_line(
                            name, m.labelnames, values, cum,
                            suffix="_bucket", extra=(("le", le),)))
                    lines.append(_sample_line(name, m.labelnames, values,
                                              child.sum(), suffix="_sum"))
                    lines.append(_sample_line(name, m.labelnames, values,
                                              child.count(),
                                              suffix="_count"))
                else:
                    lines.append(_sample_line(name, m.labelnames, values,
                                              child.value()))
        if include_monitor:
            taken = {n for n, _ in metrics}
            for key, v in sorted(monitor.all_stats().items()):
                name = re.sub(r"[^a-z0-9_]", "_", str(key).lower())
                if not _NAME_RE.match(name) or name in taken:
                    continue
                taken.add(name)
                lines.append(f"# TYPE {name} untyped")
                lines.append(f"{name} {_fmt(float(v))}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


def counter(name, help="", labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_LATENCY_BUCKETS):
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def snapshot(include_monitor=True):
    return REGISTRY.snapshot(include_monitor)


def render_prometheus(include_monitor=True):
    return REGISTRY.render_prometheus(include_monitor)


def snapshot_history():
    """The utils/timeseries history payload of the process-wide sampler
    (what /metrics/history serves); an empty payload before any sampler
    is installed."""
    from . import timeseries
    s = timeseries.get_sampler()
    return s.history() if s is not None else timeseries.empty_history()


def value(name, labels=None, default=None):
    """Read one sample from the default registry: counter/gauge value, or
    histogram observation count. `default` when the metric or the label
    series is missing — reading never creates a series."""
    m = REGISTRY.get(name)
    if m is None:
        return default
    child = m.peek(**(labels or {}))
    if child is None:
        return default
    return child.count() if m.kind == "histogram" else child.value()


# ---------------------------------------------------------------------------
# XLA compile-event tracking
# ---------------------------------------------------------------------------

XLA_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
XLA_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_XLA_COMPILES = counter(
    "xla_compiles_total",
    "XLA backend compilations per attributed function (persistent-cache "
    "loads count too: a new executable entered the process either way)",
    labelnames=("function",))
_XLA_COMPILE_SECONDS = histogram(
    "xla_compile_seconds", "XLA backend compile/cache-load durations",
    buckets=exponential_buckets(0.01, 2.0, 12))
_XLA_CACHE_HITS = counter(
    "xla_persistent_cache_hits_total",
    "Compiled executables loaded from the persistent compilation cache")

_tl = threading.local()
_install_lock = threading.Lock()
_install_state = {"installed": None}


def _compile_label(metadata_name=None):
    stack = getattr(_tl, "stack", None)
    if stack:
        return stack[-1]
    return metadata_name or "unattributed"


def _on_compile_duration(event, duration, **kw):
    if event != XLA_BACKEND_COMPILE_EVENT:
        return
    label = _compile_label(kw.get("fun_name"))
    _XLA_COMPILES.labels(label).inc()
    _XLA_COMPILE_SECONDS.observe(duration)


def _on_event(event, **kw):
    if event == XLA_CACHE_HIT_EVENT:
        _XLA_CACHE_HITS.inc()


def install_compile_tracking():
    """Register the jax.monitoring listeners (idempotent). Returns True
    when live; False on jax builds without jax.monitoring — callers fall
    back to _cache_size() deltas (instrument_jit does automatically)."""
    with _install_lock:
        if _install_state["installed"] is None:
            try:
                import jax.monitoring as jmon
                jmon.register_event_duration_secs_listener(
                    _on_compile_duration)
                jmon.register_event_listener(_on_event)
                _install_state["installed"] = True
            except Exception:        # pragma: no cover - old jax fallback
                _install_state["installed"] = False
        return _install_state["installed"]


@contextlib.contextmanager
def track_compiles(label):
    """Attribute every XLA compile event fired inside the block (from
    this thread) to `label` in xla_compiles_total{function=label}."""
    _check_name(label)
    install_compile_tracking()
    stack = getattr(_tl, "stack", None)
    if stack is None:
        stack = _tl.stack = []
    stack.append(label)
    try:
        yield
    finally:
        stack.pop()


class _InstrumentedJit:
    """Proxy over a jitted callable: calls run under
    track_compiles(label); without jax.monitoring it counts
    `_cache_size()` growth instead (the wrap-jit fallback). Attribute
    access (lower, _cache_size, ...) passes through."""

    def __init__(self, fn, label):
        _check_name(label)
        self._fn = fn
        self.label = label
        self._monitoring = install_compile_tracking()

    def __call__(self, *args, **kw):
        if self._monitoring:
            with track_compiles(self.label):
                return self._fn(*args, **kw)
        before = self._safe_cache_size()
        out = self._fn(*args, **kw)
        grew = self._safe_cache_size() - before
        if grew > 0:
            _XLA_COMPILES.labels(self.label).inc(grew)
        return out

    def _safe_cache_size(self):
        try:
            return self._fn._cache_size()
        except Exception:
            return 0

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"instrument_jit({self._fn!r}, label={self.label!r})"


def instrument_jit(fn, label):
    """Wrap a jax.jit callable so its compilations show up as
    xla_compiles_total{function=label} (the serving engine labels its
    decode wave / prefill programs this way)."""
    return _InstrumentedJit(fn, label)


def compile_count(function):
    """Live compile count for an attributed function label."""
    return int(value("xla_compiles_total", {"function": function}, 0) or 0)


# ---------------------------------------------------------------------------
# request-correlated tracing (chrome async spans + flow events)
# ---------------------------------------------------------------------------

_SPAN_STATES = ("QUEUED", "PREFILL", "DECODE")


def trace_request(request, state, reason=None):
    """Emit the chrome-trace events for one Request lifecycle transition:
    close the previous async span, open the new one (QUEUED/PREFILL/
    DECODE), and add a flow event (`s` at QUEUED, `t` in between, `f` at
    DONE/REJECTED) binding the request's arrow across the timeline. All
    events share id=trace_id and cat "serving.request"; no-op unless the
    host profiler is recording."""
    if not profiler.trace_enabled():
        return
    gen = profiler.trace_generation()
    if getattr(request, "_trace_gen", None) != gen:
        # first emission into a NEW trace buffer: any open span / flow
        # start this request remembers died with the old buffer — reset
        # so we never emit an 'e'/'t'/'f' whose partner is gone
        request._trace_span = None
        request._trace_started = False
        request._trace_gen = gen
    rid = int(getattr(request, "trace_id", 0)
              or getattr(request, "request_id", 0))
    # pid 0 = single-engine/host; fleet replicas stamp their requests
    # with trace_pid = replica_id + 1 so one merged trace shows each
    # replica's lifecycle spans on its own process row
    base = {"cat": "serving.request", "id": rid,
            "pid": int(getattr(request, "trace_pid", 0)),
            "tid": threading.get_ident() % 10000, "ts": profiler.now_us()}
    open_span = getattr(request, "_trace_span", None)
    if open_span is not None and open_span != state:
        profiler.emit_trace_event({**base, "ph": "e", "name": open_span})
    if state in _SPAN_STATES:
        profiler.emit_trace_event({**base, "ph": "b", "name": state})
        request._trace_span = state
    else:
        request._trace_span = None
    ph = "s" if state == "QUEUED" else (
        "f" if state in ("DONE", "REJECTED") else "t")
    if ph != "s" and not getattr(request, "_trace_started", False):
        return    # e.g. rejected before admission: no dangling flow-finish
    request._trace_started = ph != "f"
    flow = {**base, "ph": ph, "name": "request",
            "args": {"state": state, "request_id": rid}}
    if ph == "f":
        flow["bp"] = "e"
    if reason:
        flow["args"]["finish_reason"] = reason
    profiler.emit_trace_event(flow)


def trace_flow_step(trace_id, state, pid=0, **args):
    """Mid-flow chrome step ('t') for a fleet-level transition the
    replica-local Request lifecycle cannot see: DISPATCH (the router
    handed the request to a replica) and MIGRATE (a dead replica's hop
    was resubmitted elsewhere). Shares cat/id/name with trace_request's
    flow events, so the request's arrow runs QUEUED → DISPATCH →
    PREFILL → DECODE → (MIGRATE → next replica's spans) → DONE across
    process rows in one merged trace. No-op unless recording."""
    if not profiler.trace_enabled():
        return
    profiler.emit_trace_event({
        "cat": "serving.request", "id": int(trace_id), "ph": "t",
        "name": "request", "pid": int(pid),
        "args": {"state": str(state), **args}})


def trace_instant(trace_id, name, pid=0, **args):
    """Request-correlated chrome instant event ('i', thread-scoped) —
    the paged engine marks each PREFILL_CHUNK[i] it runs this way, so a
    chunked admission's progress is visible inside the PREFILL span.
    No-op unless recording."""
    if not profiler.trace_enabled():
        return
    profiler.emit_trace_event({
        "cat": "serving.request", "id": int(trace_id), "ph": "i",
        "s": "t", "name": str(name), "pid": int(pid),
        "args": dict(args) if args else {}})


# ---------------------------------------------------------------------------
# /metrics exporter (stdlib http.server, background thread)
# ---------------------------------------------------------------------------

_debug_requests_provider = None


def set_debug_requests_provider(fn):
    """Install the `/debug/requests` payload provider. The serving
    black-box recorder (serving/blackbox.py) registers itself at import
    time — utils must not import serving, so the endpoint reaches the
    journal through this hook. `fn` takes no arguments and returns a
    JSON-safe dict; None detaches (the endpoint then serves an empty
    trace list)."""
    global _debug_requests_provider
    with _install_lock:
        _debug_requests_provider = fn


def _debug_requests_body():
    fn = _debug_requests_provider
    if fn is None:
        return {"recording": False, "requests": []}
    try:
        return fn()
    except Exception as e:   # noqa: BLE001 - report, not die
        return {"recording": False, "requests": [], "error": repr(e)}


def make_metrics_handler(registry=None, health_fn=None, sampler=None):
    reg = registry or REGISTRY

    def _history():
        # the handler-bound sampler wins; otherwise the process-wide
        # install (utils/timeseries) is resolved per request, so a
        # server started before the sampler still serves its history
        from . import timeseries
        s = sampler or timeseries.get_sampler()
        return s.history() if s is not None else timeseries.empty_history()

    class Handler(http.server.BaseHTTPRequestHandler):
        server_version = "paddle-tpu-telemetry/1.0"

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = reg.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif path == "/metrics.json":
                body = json.dumps(reg.snapshot()).encode()
                ctype = "application/json"
                code = 200
            elif path == "/metrics/history":
                # sorted keys + no timestamps anywhere in the payload:
                # identical sampled values serve identical BYTES
                # (tests pin this determinism)
                body = json.dumps(_history(), sort_keys=True).encode()
                ctype = "application/json"
                code = 200
            elif path == "/dashboard":
                from . import timeseries
                body = timeseries.render_dashboard(_history()).encode()
                ctype = "text/html; charset=utf-8"
                code = 200
            elif path == "/debug/requests":
                # sorted keys, timestamp-free payload — same bytes
                # discipline as /metrics/history
                body = json.dumps(_debug_requests_body(),
                                  sort_keys=True).encode()
                ctype = "application/json"
                code = 200
            elif path == "/healthz":
                payload = {"status": "ok", "time_unix": time.time()}
                if health_fn is not None:
                    try:
                        payload.update(health_fn() or {})
                    except Exception as e:   # noqa: BLE001 - report, not die
                        payload["status"] = "degraded"
                        payload["error"] = repr(e)
                body = json.dumps(payload).encode()
                ctype = "application/json"
                # status-code-probing load balancers (the k8s httpGet
                # default) never parse the body — a degraded/draining
                # engine must fail the probe, not answer 200 with a
                # sad JSON inside
                code = 200 if payload.get("status") == "ok" else 503
            else:
                body = (b"not found; try /metrics /metrics.json "
                        b"/metrics/history /dashboard /debug/requests "
                        b"/healthz\n")
                ctype = "text/plain"
                code = 404
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):      # keep the serving loop's stdout
            pass

    return Handler


def http_get_inline(path="/metrics", registry=None, health_fn=None,
                    sampler=None):
    """Drive the metrics handler fully in-process (no socket): returns
    (status_code, headers_dict, body_bytes). Tests exercise the exporter
    exactly as an HTTP client would, without binding a port."""

    class _FakeSocket:
        """socketserver writes either via makefile('wb') or, for the
        unbuffered default, via sendall() — capture both into one
        buffer that survives close()."""

        def __init__(self):
            self._rd = io.BytesIO(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            self.out = bytearray()
            outer = self

            class _Wr(io.RawIOBase):
                def writable(self):
                    return True

                def write(self, data):
                    outer.out += bytes(data)
                    return len(data)

            self._wr = io.BufferedWriter(_Wr())

        def makefile(self, mode, *a, **kw):
            return self._rd if "r" in mode else self._wr

        def sendall(self, data):
            self.out += bytes(data)

    sock = _FakeSocket()
    make_metrics_handler(registry, health_fn,
                         sampler=sampler)(sock, ("127.0.0.1", 0), None)
    raw = bytes(sock.out)
    head, _, body = raw.partition(b"\r\n\r\n")
    head_lines = head.decode("latin-1").split("\r\n")
    status = int(head_lines[0].split()[1])
    headers = {}
    for ln in head_lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, body


class MetricsServer:
    """Background /metrics exporter over stdlib http.server.

        srv = MetricsServer(port=9100).start()   # port=0 picks a free one
        ... srv.url, srv.port ...
        srv.stop()

    health_fn (optional) returns extra key/values merged into the
    /healthz payload (the serving engine reports slot state there)."""

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 health_fn=None, sampler=None):
        self.registry = registry or REGISTRY
        self.host = host
        self.port = int(port)
        self.health_fn = health_fn
        self.sampler = sampler
        self._httpd = None
        self._thread = None

    def start(self):
        if self._httpd is not None:
            return self
        handler = make_metrics_handler(self.registry, self.health_fn,
                                       sampler=self.sampler)
        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
