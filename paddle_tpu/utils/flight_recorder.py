"""Training flight recorder: append-only JSONL run journal.

The serving path got full telemetry in the observability PR; this module
is the training-side counterpart — a crash-surviving record of what a
run actually did, step by step:

  * `FlightRecorder` writes one JSON object per line (`run_start`,
    `step`, `compile`, `nonfinite`, `collective`, `checkpoint`,
    `xla_program`, `jxaudit`, `run_end`). Events are ring-buffered
    (`ring_size`) between disk
    flushes, so a pathological run keeps bounded memory/IO and the LAST
    N events — the ones that explain the crash — always reach the
    journal: the context manager flushes on exception and appends a
    `run_end {status: "crashed"}` marker.
  * `jit.TrainStep.attach_flight_recorder` threads it through training:
    every step event carries the data-wait / host-dispatch / device-time
    split, loss, global grad norm, the non-finite sentinel, and MFU from
    the compiled executable's cost analysis (`cost_analysis` below —
    computed once per executable, cached by input signature).
  * `hapi.Model.fit(flight_recorder=...)` owns the run lifecycle
    (run_start/run_end, flush-on-exception) and measures data wait.
  * `amp.GradScaler`, `distributed.collective`, and `Model.save` emit
    `nonfinite` / `collective` / `checkpoint` events through the
    module-level *current recorder* (`set_recorder`/`get_recorder`) so
    deep layers need no plumbing.

`scripts/runlog_summary.py` renders a journal into a report;
`rollup()` is the compact version bench entrypoints attach to their
output. Journal schema is documented in docs/observability.md.
"""
import collections
import contextlib
import json
import os
import threading
import time


class NonFiniteError(RuntimeError):
    """Raised by fail-fast training when loss/grad-norm go non-finite."""


EVENT_KINDS = ("run_start", "step", "compile", "nonfinite", "collective",
               "checkpoint", "xla_program", "jxaudit", "shaudit", "chaos",
               "fault", "resume", "reshard", "hang", "slo", "alert",
               "spec", "run_end")

#: every `kind=` a `fault` event may carry.  The closed vocabulary is
#: what makes journals greppable and the runlog summarizer's fault
#: rollup stable; a NEW kind must be added here AND documented in
#: docs/observability.md — the `event-kind-documented` ptlint rule
#: enforces both at every literal call site.  The `replica_killed` /
#: `replica_degraded` pair is emitted dynamically by the fleet router
#: ("replica_" + retire reason), so the members are declared here even
#: though no literal call site spells them out.
FAULT_KINDS = ("nonfinite", "wave_error", "prefill_error",
               "callback_error", "token_mask_error", "cache_exhausted",
               "handoff_refused", "handoff_error", "degraded",
               "collective_error", "reshard_config_drift",
               "replica_killed", "replica_degraded", "replica_migration",
               "replica_handoff", "replica_spawn_failed")


def _json_safe(v):
    """JSON has no NaN/Inf literal; a diverged loss is exactly when the
    journal must stay parseable — spell non-finite floats as strings
    (same convention as telemetry's JSON snapshot)."""
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return "NaN" if v != v else ("+Inf" if v > 0 else "-Inf")
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


class FlightRecorder:
    """Ring-buffered JSONL journal writer.

        rec = FlightRecorder("runlog.jsonl")
        with rec:                      # run_start ... run_end bracketing
            rec.step(step=1, data_s=.001, host_s=.002, device_s=.03,
                     loss=2.3, mfu=0.41)

    `path=None` keeps events in memory only (bench rollups).
    `flush_every` defers disk writes; between flushes at most `ring_size`
    events are retained (oldest dropped, counted in `run_end`), so the
    last steps before a crash always survive — the flight-recorder
    contract. `fail_fast` is advisory state consumed by TrainStep: a
    non-finite step raises `NonFiniteError` instead of training on.
    """

    def __init__(self, path=None, ring_size=512, flush_every=1,
                 fail_fast=False, meta=None):
        self.path = os.fspath(path) if path is not None else None
        self.ring_size = max(1, int(ring_size))
        self.flush_every = max(1, int(flush_every))
        self.fail_fast = bool(fail_fast)
        self.meta = dict(meta or {})
        self._lock = threading.RLock()
        self._pending = collections.deque(maxlen=self.ring_size)
        self._recent = collections.deque(maxlen=self.ring_size)
        self._counts = {}
        self._dropped = 0
        self._seq = 0
        self._file = None
        self._started = False
        self._ended = False
        self.run_id = None

    # ---------------------------------------------------------------- core
    def record(self, event, **fields):
        """Append one event of kind `event`; returns the dict written
        (ts/seq added). The parameter is named `event`, not `kind`, so
        typed events (`fault`) may carry their own `kind` field."""
        with self._lock:
            self._seq += 1
            ev = {"ev": event, "ts": round(time.time(), 6),
                  "seq": self._seq}
            ev.update(_json_safe(fields))
            self._recent.append(ev)
            self._counts[event] = self._counts.get(event, 0) + 1
            if self.path is not None:
                if len(self._pending) == self._pending.maxlen:
                    self._dropped += 1    # ring full: oldest pending falls
                self._pending.append(ev)
                if len(self._pending) >= self.flush_every:
                    self.flush()
            return ev

    def flush(self):
        """Write buffered events to the journal file (no-op in-memory)."""
        if self.path is None:
            return
        with self._lock:
            if not self._pending:
                return
            if self._file is None:
                self._file = open(self.path, "a")
            while self._pending:
                self._file.write(
                    json.dumps(self._pending.popleft(), allow_nan=False)
                    + "\n")
            self._file.flush()

    def close(self):
        with self._lock:
            self.flush()
            if self._file is not None:
                self._file.close()
                self._file = None

    def events(self):
        """The last `ring_size` events, flushed or not (bench rollups)."""
        with self._lock:
            return list(self._recent)

    def counts(self):
        with self._lock:
            return dict(self._counts)

    @property
    def dropped_events(self):
        return self._dropped

    # ------------------------------------------------------------- typed
    def run_start(self, **meta):
        """Open a run. Idempotent while a run is open (fit and `with`
        both call it); after run_end it opens a NEW run segment in the
        same journal, so reusing one recorder across two fits brackets
        each run instead of silently recording neither."""
        import uuid
        with self._lock:
            if self._started and not self._ended:
                return None
            self._started, self._ended = True, False
            # a fresh id per run segment: checkpoints record it so a
            # resumed run's `resume` event names the run it continues
            self.run_id = uuid.uuid4().hex[:12]
        info = dict(self.meta)
        info.update(meta)
        return self.record("run_start", run_id=self.run_id, **info)

    def run_end(self, status="ok", error=None, **extra):
        """Close the run (idempotent) and force a flush — crashed runs
        keep their last `ring_size` events on disk."""
        with self._lock:
            if self._ended:
                return None
            self._ended = True
        fields = {"status": status, "counts": self.counts(),
                  "dropped_events": self._dropped}
        if error:
            fields["error"] = str(error)
        fields.update(extra)
        ev = self.record("run_end", **fields)
        self.flush()
        return ev

    def step(self, step, data_s, host_s, device_s, loss=None, grad_norm=None,
             mfu=None, nonfinite=False, **extra):
        return self.record(
            "step", step=int(step), data_s=round(float(data_s), 6),
            host_s=round(float(host_s), 6),
            device_s=round(float(device_s), 6),
            loss=None if loss is None else float(loss),
            grad_norm=None if grad_norm is None else float(grad_norm),
            mfu=None if mfu is None else float(mfu),
            nonfinite=bool(nonfinite), **extra)

    def compile_event(self, label, count=1, compile_s=None, flops=None,
                      bytes_accessed=None, **extra):
        fields = {"label": str(label), "count": int(count)}
        if compile_s is not None:
            fields["compile_s"] = round(float(compile_s), 6)
        if flops is not None:
            fields["flops"] = float(flops)
        if bytes_accessed is not None:
            fields["bytes_accessed"] = float(bytes_accessed)
        fields.update(extra)
        return self.record("compile", **fields)

    def nonfinite(self, step=None, loss=None, grad_norm=None,
                  source="train_step", **extra):
        fields = {"source": str(source)}
        if step is not None:
            fields["step"] = int(step)
        if loss is not None:
            fields["loss"] = float(loss)
        if grad_norm is not None:
            fields["grad_norm"] = float(grad_norm)
        fields.update(extra)
        return self.record("nonfinite", **fields)

    def collective(self, op, nbytes, group="default", traced=False, **extra):
        return self.record("collective", op=str(op), bytes=int(nbytes),
                           group=str(group), traced=bool(traced), **extra)

    def xla_program(self, program, flops=None, bytes_accessed=None,
                    peak_memory_bytes=None, fusion_count=None, **extra):
        """Compile-level audit result for one tracked program (the
        xprof observatory's journal hook — rides next to the `compile`
        events so one journal shows both when a program compiled and
        what the compiler made of it). None fields are journaled as
        null: 'analysis unavailable' is itself a recorded fact."""
        return self.record(
            "xla_program", program=str(program),
            flops=None if flops is None else float(flops),
            bytes_accessed=(None if bytes_accessed is None
                            else float(bytes_accessed)),
            peak_memory_bytes=(None if peak_memory_bytes is None
                               else float(peak_memory_bytes)),
            fusion_count=(None if fusion_count is None
                          else int(fusion_count)), **extra)

    def jxaudit(self, findings, by_rule=None, programs=None,
                degraded=None, **extra):
        """Semantic-audit verdict for the tracked programs (the jxaudit
        journal hook — rides next to compile / xla_program events so
        one journal shows what compiled, what it cost, and whether its
        semantics audit clean). `by_rule` maps rule id -> finding
        count; zero findings journals as a clean stamp, not silence."""
        fields = {"findings": int(findings),
                  "by_rule": {str(k): int(v)
                              for k, v in sorted((by_rule or {}).items())}}
        if programs is not None:
            fields["programs"] = int(programs)
        if degraded is not None:
            fields["degraded"] = int(degraded)
        fields.update(extra)
        return self.record("jxaudit", **fields)

    def shaudit(self, findings, by_rule=None, programs=None,
                degraded=None, wasted_replicated_bytes=None,
                collective_breaches=None, **extra):
        """Mesh-aware sharding-audit verdict for the pjit'd sharded
        programs (the shaudit journal hook). Beyond the jxaudit fields,
        `wasted_replicated_bytes` totals the accidental-replication
        waste across findings and `collective_breaches` counts
        collective-budget violations — zero findings journals as a
        clean stamp, not silence."""
        fields = {"findings": int(findings),
                  "by_rule": {str(k): int(v)
                              for k, v in sorted((by_rule or {}).items())}}
        if programs is not None:
            fields["programs"] = int(programs)
        if degraded is not None:
            fields["degraded"] = int(degraded)
        if wasted_replicated_bytes is not None:
            fields["wasted_replicated_bytes"] = int(wasted_replicated_bytes)
        if collective_breaches is not None:
            fields["collective_breaches"] = int(collective_breaches)
        fields.update(extra)
        return self.record("shaudit", **fields)

    def chaos(self, point, action, invocation=None, **extra):
        """An injected fault fired (utils.chaos) — journaled so a
        recovered run shows the injection next to the `fault` events
        the resilience layer wrote while handling it."""
        fields = {"point": str(point), "action": str(action)}
        if invocation is not None:
            fields["invocation"] = int(invocation)
        fields.update(extra)
        return self.record("chaos", **fields)

    def fault(self, kind, action=None, request_id=None, slot=None,
              error=None, **extra):
        """The resilience layer handled a fault: `kind` names the fault
        class (nonfinite / wave_error / prefill_error / callback_error /
        degraded), `action` what was done about it (retired / retry /
        degraded / shed)."""
        fields = {"kind": str(kind)}
        if action is not None:
            fields["action"] = str(action)
        if request_id is not None:
            fields["request_id"] = int(request_id)
        if slot is not None:
            fields["slot"] = int(slot)
        if error is not None:
            fields["error"] = str(error)
        fields.update(extra)
        return self.record("fault", **fields)

    def resume(self, prior_run_id=None, step=None, epoch=None, batch=None,
               **extra):
        """This run continues a checkpointed prior run: `prior_run_id`
        is the `run_start.run_id` of the run that wrote the checkpoint,
        `step` the global step being resumed from, epoch/batch the data
        cursor the fast-forward targets — journaled next to `run_start`
        so trajectory stitching is reconstructable from journals alone."""
        fields = {}
        if prior_run_id is not None:
            fields["prior_run_id"] = str(prior_run_id)
        if step is not None:
            fields["step"] = int(step)
        if epoch is not None:
            fields["epoch"] = int(epoch)
        if batch is not None:
            fields["batch"] = int(batch)
        fields.update(extra)
        return self.record("resume", **fields)

    def reshard(self, from_mesh=None, to_mesh=None, from_dp=None,
                to_dp=None, zero_stage=None, **extra):
        """This resume relaid sharded training state onto a DIFFERENT
        mesh than the checkpoint was written on (elastic reshard):
        from/to mesh shape dicts, the dp sizes on the checkpoint's dp
        axis, and the checkpoint's ZeRO stage — journaled right after
        the `resume` event so a trajectory stitched across a reshard
        names both layouts (utils/resume.maybe_record_reshard)."""
        fields = {}
        if from_mesh is not None:
            fields["from_mesh"] = {str(k): int(v)
                                   for k, v in dict(from_mesh).items()}
        if to_mesh is not None:
            fields["to_mesh"] = {str(k): int(v)
                                 for k, v in dict(to_mesh).items()}
        if from_dp is not None:
            fields["from_dp"] = int(from_dp)
        if to_dp is not None:
            fields["to_dp"] = int(to_dp)
        if zero_stage is not None:
            fields["zero_stage"] = int(zero_stage)
        fields.update(extra)
        return self.record("reshard", **fields)

    def hang(self, age_s, threshold_s=None, step=None, action="observe",
             stacks=None, **extra):
        """The training watchdog (utils/resume.TrainWatchdog) detected a
        stalled step: no step completed for `age_s` seconds against a
        rolling-step-time threshold. `stacks` carries the thread stack
        dumps captured at detection; `action` is "observe" or
        "interrupt" (deadline exceeded, KeyboardInterrupt raised into
        the main thread)."""
        fields = {"age_s": round(float(age_s), 3), "action": str(action)}
        if threshold_s is not None:
            fields["threshold_s"] = round(float(threshold_s), 3)
        if step is not None:
            fields["step"] = int(step)
        if stacks is not None:
            fields["stacks"] = stacks
        fields.update(extra)
        return self.record("hang", **fields)

    def slo(self, burn_rate, action, attainment=None, slo=None,
            window_requests=None, **extra):
        """The SLO engine's burn-rate state changed (serving/slo.py):
        `action` names the transition — "burn_alert" (burn rate crossed
        the fast-burn threshold), "burn_clear" (it came back under
        budget), "scale_up"/"scale_down" (the fleet autoscaler acted on
        it). `slo` names the worst target driving the verdict. Journaled
        on TRANSITIONS, not per evaluation, so a long breach is two
        lines, not a flood."""
        fields = {"burn_rate": round(float(burn_rate), 4),
                  "action": str(action)}
        if attainment is not None:
            fields["attainment"] = round(float(attainment), 6)
        if slo is not None:
            fields["slo"] = str(slo)
        if window_requests is not None:
            fields["window_requests"] = int(window_requests)
        fields.update(extra)
        return self.record("slo", **fields)

    def alert(self, rule, action, severity=None, **detail):
        """An AlertManager rule transitioned (utils/anomaly.py):
        `action` is "firing" (the detector tripped) or "cleared" (it
        recovered).  Journaled on TRANSITIONS only — the same
        discipline as the SLO engine's burn alerts, so a sustained
        anomaly is two lines, not a per-round flood.  `detail` carries
        the detector's evidence (value, z-score, the function that
        recompiled, the skew ratio, ...)."""
        fields = {"rule": str(rule), "action": str(action)}
        if severity is not None:
            fields["severity"] = str(severity)
        fields.update(detail)
        return self.record("alert", **fields)

    def spec(self, proposed, accepted, lanes=None, spec_depth=None,
             **extra):
        """One speculative decode wave's draft economics (the serving
        scheduler journals this next to its fault events): `proposed` =
        draft tokens offered to the verify program, `accepted` = how
        many the exact acceptance-rejection kept, `lanes` = slots the
        wave dispatched, `spec_depth` = accepted per dispatched lane.
        runlog_summary folds these into a per-run acceptance table."""
        fields = {"proposed": int(proposed), "accepted": int(accepted)}
        if lanes is not None:
            fields["lanes"] = int(lanes)
        if spec_depth is not None:
            fields["spec_depth"] = float(spec_depth)
        fields.update(extra)
        return self.record("spec", **fields)

    def checkpoint(self, path=None, step=None, **extra):
        fields = {}
        if path is not None:
            fields["path"] = str(path)
        if step is not None:
            fields["step"] = int(step)
        fields.update(extra)
        return self.record("checkpoint", **fields)

    # --------------------------------------------------------- lifecycle
    def __enter__(self):
        self._prev = set_recorder(self)
        self.run_start()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.run_end(status="crashed",
                         error=f"{exc_type.__name__}: {exc}")
        else:
            self.run_end(status="ok")
        set_recorder(getattr(self, "_prev", None))
        self.close()
        return False


# ---------------------------------------------------------------------------
# current recorder (so amp / collective / save need no plumbing)
# ---------------------------------------------------------------------------

_current_lock = threading.Lock()
_current = None


def set_recorder(recorder):
    """Install `recorder` as the process-wide current recorder; returns
    the previous one (restore it when done)."""
    global _current
    with _current_lock:
        prev = _current
        _current = recorder
        return prev


def get_recorder():
    return _current


@contextlib.contextmanager
def recording(recorder):
    prev = set_recorder(recorder)
    try:
        yield recorder
    finally:
        set_recorder(prev)


# ---------------------------------------------------------------------------
# journal readers / rollup
# ---------------------------------------------------------------------------

def read_journal(path):
    """Parse a JSONL journal -> list of event dicts (strict: a malformed
    line raises — the writer emits one valid object per line)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def rollup(events):
    """Compact summary for bench output: steps, mean MFU over steps that
    have one, executable (re)compiles, and non-finite incidents."""
    steps = [e for e in events if e.get("ev") == "step"]
    mfus = [e["mfu"] for e in steps
            if isinstance(e.get("mfu"), (int, float)) and e["mfu"] > 0]
    return {
        "steps": len(steps),
        "mean_mfu": round(sum(mfus) / len(mfus), 4) if mfus else 0.0,
        "recompiles": sum(int(e.get("count", 1)) for e in events
                          if e.get("ev") == "compile"),
        "nonfinite": sum(1 for e in events if e.get("ev") == "nonfinite"),
    }


# ---------------------------------------------------------------------------
# cost accounting (MFU)
# ---------------------------------------------------------------------------

# bf16 peak dense FLOP/s by TPU device kind substring (first match wins);
# CPU/unknown fall back to a nominal 1 TF/s so MFU stays a defined,
# comparable-across-runs number even off-chip (flagged by peak source).
_PEAK_FLOPS_BY_KIND = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_DEFAULT_PEAK_FLOPS = 1e12


def device_peak_flops(device=None):
    """Peak FLOP/s of the accelerator MFU is measured against.
    `PT_PEAK_FLOPS` (float, FLOP/s) overrides the table for parts not
    listed here."""
    env = os.environ.get("PT_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        dev = device or jax.local_devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").lower()
    except Exception:
        return _DEFAULT_PEAK_FLOPS
    for key, peak in _PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return _DEFAULT_PEAK_FLOPS


# peak HBM bandwidth (bytes/s) by TPU device kind substring — the
# denominator of the serving roofline's bandwidth axis, the way
# _PEAK_FLOPS_BY_KIND is the compute axis. CPU/unknown fall back to a
# nominal 100 GB/s so serving_hbm_util stays a defined,
# comparable-across-runs number off-chip (same policy as MFU).
_PEAK_HBM_BW_BY_KIND = (
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),
    ("v5litepod", 819e9),
    ("v6e", 1640e9),
    ("trillium", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)
_DEFAULT_PEAK_HBM_BW = 100e9


def device_peak_hbm_bw(device=None):
    """Peak HBM bandwidth (bytes/s) of the accelerator the serving
    bandwidth-utilization gauge is measured against. `PT_PEAK_HBM_BW`
    (float, bytes/s) overrides the table for parts not listed."""
    env = os.environ.get("PT_PEAK_HBM_BW")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        dev = device or jax.local_devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").lower()
    except Exception:
        return _DEFAULT_PEAK_HBM_BW
    for key, peak in _PEAK_HBM_BW_BY_KIND:
        if key in kind:
            return peak
    return _DEFAULT_PEAK_HBM_BW


def normalize_cost_analysis(ca):
    """Normalize a raw `cost_analysis()` result to one shape.

    Across jax versions/backends the call returns a dict, a
    list-of-dicts (one per device/partition — the first carries the
    program totals), or something unusable; keys use XLA's spaced
    spelling ("bytes accessed"). This is THE one place that shape
    knowledge lives — jit.TrainStep, the xprof audit and
    scripts/mosaic_check.py all consume this normalized form. Returns
    {"flops": float, "bytes_accessed": float, "transcendentals": float}
    (keys present when the analysis provides a numeric value, never
    NaN), or None when nothing usable came back."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    out = {}
    for key, spelled in (("flops", "flops"),
                         ("bytes_accessed", "bytes accessed"),
                         ("transcendentals", "transcendentals")):
        v = ca.get(spelled, ca.get(key))
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and v == v:
            out[key] = float(v)
    return out or None


def cost_analysis(jitted, *args, **kwargs):
    """FLOPs/bytes of the executable `jitted(*args)` would run, via the
    lowering's HLO cost analysis — no second backend compile, and safe
    to call with the concrete (not-yet-donated) call arguments. Returns
    the `normalize_cost_analysis` dict or None when the jax
    build/backend can't analyze."""
    try:
        lowered = jitted.lower(*args, **kwargs)
        ca = lowered.cost_analysis()
    except Exception:
        return None
    return normalize_cost_analysis(ca)
