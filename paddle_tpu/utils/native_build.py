"""Lazy builder/loader for the native C++ runtime library
(native/src/*.cc -> libptnative.so), the cpp_extension JIT-build analog
(ref python/paddle/utils/cpp_extension/: compile-on-demand with caching).

No pybind11 in the image — the library exposes a C ABI consumed via ctypes.
"""
import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native"))


def native_dir():
    return _NATIVE_DIR


def _needs_build(out, srcs):
    if not os.path.exists(out):
        return True
    out_m = os.path.getmtime(out)
    return any(os.path.getmtime(s) > out_m for s in srcs)


def build_native(verbose=False):
    """Compile native/src/*.cc into build/libptnative.so if stale."""
    src_dir = os.path.join(_NATIVE_DIR, "src")
    srcs = sorted(os.path.join(src_dir, f) for f in os.listdir(src_dir)
                  if f.endswith(".cc"))
    out = os.path.join(_NATIVE_DIR, "build", "libptnative.so")
    if not _needs_build(out, srcs):
        return out
    os.makedirs(os.path.dirname(out), exist_ok=True)
    cmd = [os.environ.get("CXX", "g++"), "-O2", "-fPIC", "-std=c++17",
           "-Wall", "-pthread", "-shared", *srcs, "-o", out]
    if verbose:
        print("building native lib:", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{proc.stderr}\ncmd: {' '.join(cmd)}")
    return out


def load_native():
    """Build (if needed) and dlopen the native library; cached."""
    global _lib
    with _lock:
        if _lib is None:
            path = build_native()
            lib = ctypes.CDLL(path)
            _configure(lib)
            _lib = lib
    return _lib


def _configure(lib):
    c = ctypes
    lib.pt_feed_create.restype = c.c_void_p
    lib.pt_feed_destroy.argtypes = [c.c_void_p]
    lib.pt_feed_add_slot.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.pt_feed_load_file.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_feed_load_file.restype = c.c_long
    lib.pt_feed_error.argtypes = [c.c_void_p]
    lib.pt_feed_error.restype = c.c_char_p
    lib.pt_feed_shuffle.argtypes = [c.c_void_p, c.c_ulonglong]
    lib.pt_feed_size.argtypes = [c.c_void_p]
    lib.pt_feed_size.restype = c.c_long
    lib.pt_feed_clear.argtypes = [c.c_void_p]
    lib.pt_feed_start.argtypes = [c.c_void_p, c.c_int, c.c_int, c.c_int]
    lib.pt_feed_next.argtypes = [c.c_void_p]
    lib.pt_feed_next.restype = c.c_int
    lib.pt_feed_stop.argtypes = [c.c_void_p]
    f32p = c.POINTER(c.c_float)
    i64p = c.POINTER(c.c_int64)
    lib.pt_feed_slot_fvals.argtypes = [c.c_void_p, c.c_int, c.POINTER(f32p)]
    lib.pt_feed_slot_fvals.restype = c.c_long
    lib.pt_feed_slot_ivals.argtypes = [c.c_void_p, c.c_int, c.POINTER(i64p)]
    lib.pt_feed_slot_ivals.restype = c.c_long
    lib.pt_feed_slot_lod.argtypes = [c.c_void_p, c.c_int, c.POINTER(i64p)]
    lib.pt_feed_slot_lod.restype = c.c_long

    # ---- parameter server (native/src/ps_server.cc)
    lib.pt_ps_server_create.restype = c.c_void_p
    lib.pt_ps_server_destroy.argtypes = [c.c_void_p]
    lib.pt_ps_add_dense_table.argtypes = [c.c_void_p, c.c_uint32, c.c_int64,
                                          c.c_float]
    lib.pt_ps_add_sparse_table.argtypes = [c.c_void_p, c.c_uint32, c.c_int,
                                           c.c_float, c.c_float]
    lib.pt_ps_table_set_adagrad.argtypes = [c.c_void_p, c.c_uint32, c.c_int,
                                            c.c_float]
    lib.pt_ps_table_set_adagrad.restype = c.c_int
    lib.pt_ps_server_start.argtypes = [c.c_void_p, c.c_int]
    lib.pt_ps_server_start.restype = c.c_int
    lib.pt_ps_server_stop.argtypes = [c.c_void_p]
    lib.pt_ps_client_create.restype = c.c_void_p
    lib.pt_ps_client_destroy.argtypes = [c.c_void_p]
    lib.pt_ps_client_connect.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pt_ps_client_connect.restype = c.c_int
    f32p = c.POINTER(c.c_float)
    i64p = c.POINTER(c.c_int64)
    lib.pt_ps_pull_dense.argtypes = [c.c_void_p, c.c_uint32, f32p, c.c_int64]
    lib.pt_ps_pull_dense.restype = c.c_int
    lib.pt_ps_push_dense.argtypes = [c.c_void_p, c.c_uint32, f32p, c.c_int64,
                                     c.c_int]
    lib.pt_ps_push_dense.restype = c.c_int
    lib.pt_ps_pull_sparse.argtypes = [c.c_void_p, c.c_uint32, i64p, c.c_int64,
                                      f32p, c.c_int]
    lib.pt_ps_pull_sparse.restype = c.c_int
    lib.pt_ps_set_sparse.argtypes = [c.c_void_p, c.c_uint32, i64p,
                                     c.c_int64, f32p, c.c_int]
    lib.pt_ps_set_sparse.restype = c.c_int
    lib.pt_ps_add_edges.argtypes = [c.c_void_p, c.c_uint32, i64p, c.c_int64]
    lib.pt_ps_add_edges.restype = c.c_int
    lib.pt_ps_sample_neighbors.argtypes = [c.c_void_p, c.c_uint32, i64p,
                                           c.c_int64, c.c_uint32, i64p]
    lib.pt_ps_sample_neighbors.restype = c.c_int
    lib.pt_ps_get_degree.argtypes = [c.c_void_p, c.c_uint32, i64p,
                                     c.c_int64, i64p]
    lib.pt_ps_get_degree.restype = c.c_int
    lib.pt_ps_random_nodes.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32,
                                       i64p]
    lib.pt_ps_random_nodes.restype = c.c_int
    lib.pt_ps_push_sparse_grad.argtypes = [c.c_void_p, c.c_uint32, i64p,
                                           c.c_int64, f32p, c.c_int]
    lib.pt_ps_push_sparse_grad.restype = c.c_int
    lib.pt_ps_barrier.argtypes = [c.c_void_p, c.c_uint32]
    lib.pt_ps_barrier.restype = c.c_int
    lib.pt_ps_barrier_as.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32]
    lib.pt_ps_barrier_as.restype = c.c_int
    lib.pt_ps_save.argtypes = [c.c_void_p, c.c_uint32, c.c_char_p]
    lib.pt_ps_save.restype = c.c_int
    lib.pt_ps_load.argtypes = [c.c_void_p, c.c_uint32, c.c_char_p]
    lib.pt_ps_load.restype = c.c_int
    # worker liveness (heartbeat monitor)
    lib.pt_ps_server_set_heartbeat_timeout.argtypes = [c.c_void_p, c.c_int]
    lib.pt_ps_worker_register.argtypes = [c.c_void_p, c.c_uint32]
    lib.pt_ps_worker_register.restype = c.c_int
    lib.pt_ps_worker_heartbeat.argtypes = [c.c_void_p, c.c_uint32]
    lib.pt_ps_worker_heartbeat.restype = c.c_int
    lib.pt_ps_worker_complete.argtypes = [c.c_void_p, c.c_uint32]
    lib.pt_ps_worker_complete.restype = c.c_int
    lib.pt_ps_query_workers.argtypes = [c.c_void_p,
                                        c.POINTER(c.c_uint32)]
    lib.pt_ps_query_workers.restype = c.c_int
