"""Process-wide stat registry.

TPU-native analog of the reference monitor (ref
paddle/fluid/platform/monitor.h:77 StatRegistry, STAT_ADD :130): named int
counters for memory/throughput bookkeeping, queryable from python the way
the reference exposes them via pybind/global_value_getter_setter.cc.
Device memory stats come from PJRT (`jax.local_devices()[0].memory_stats()`)
instead of a custom allocator (ref memory/allocation).

These flat int stats are subsumed by `utils.telemetry`: every snapshot /
Prometheus exposition of the typed metric registry includes them, so
legacy `stat_add` call sites show up on /metrics without migration. New
code should prefer telemetry's typed Counter/Gauge/Histogram."""
import threading

_lock = threading.Lock()
_stats = {}


def stat_add(name, value=1):
    with _lock:
        _stats[name] = _stats.get(name, 0) + value
        return _stats[name]


def stat_set(name, value):
    with _lock:
        _stats[name] = value


def stat_max(name, value):
    """Atomic running max — peak-style gauges (serving queue-depth peak)
    from producer threads without a get-then-set race."""
    with _lock:
        _stats[name] = max(_stats.get(name, value), value)
        return _stats[name]


def stat_get(name, default=0):
    with _lock:
        return _stats.get(name, default)


def stat_reset(name=None):
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def all_stats():
    with _lock:
        return dict(_stats)


def device_memory_stats(device=None):
    """PJRT memory stats for a device — replaces the reference's allocator
    STAT_ADD("gpu_mem", ...) counters (memory/stats.h).

    Returns None when the backend exposes no stats (CPU jax returns None
    from `memory_stats()`): callers skip their gauges instead of
    publishing fake zeros on /metrics."""
    import jax
    dev = device or jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except (AttributeError, RuntimeError):
        return None
    if not stats:
        return None
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
    }
