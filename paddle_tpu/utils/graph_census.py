"""Graph-property census over traced train steps.

The perf claims behind the flash-attention BSHD layout and the
vocab-chunked CE are *graph* properties, checkable without TPU hardware
(round-4 verdict, next-round #2):

  - the BSHD path leaves NO bf16 attention-layout transposes around the
    qkv projections (PERF.md hotspot #1 — each costs an HBM round-trip
    of the [B,H,S,D] activation);
  - the fused head+CE never materialises a [B,S,V] logits intermediate
    (PERF.md hotspot #2 — at gpt2s b=8 that tensor is 1 GiB in f32).

census_jaxpr() walks the closed jaxpr of the jitted step (forward +
backward + optimizer), recursing through control-flow/remat/custom-vjp
sub-jaxprs but NOT into pallas kernel bodies (kernel-internal register
shuffles are free; the census measures HBM-level layout traffic), and
counts the operations that would violate each property. pytest asserts
the counts (tests/test_hlo_census.py) so the property cannot regress
while the TPU tunnel is down; scripts/scaling_probe.py applies the same
technique to the partitioned-HLO collective structure.
"""
import jax

# primitives whose sub-jaxprs are still "the program" (recurse), vs
# pallas_call whose inner jaxpr is the kernel body (skip)
_SKIP_INNER = {"pallas_call"}


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        if eqn.primitive.name in _SKIP_INNER:
            continue
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    # duck-typed: ClosedJaxpr carries .jaxpr, a raw Jaxpr carries .eqns
    if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        yield v.jaxpr
    elif hasattr(v, "eqns"):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def census_jaxpr(closed_jaxpr, seq_len, head_dim, vocab_size):
    """Count property-violating ops in a traced step.

    Returns dict with:
      attn_transposes: transpose eqns on >=4-D bf16/f16 tensors whose
        shape carries both the sequence and head dims — the layout
        round-trips the BSHD path exists to remove;
      vocab_intermediates: eqn outputs shaped like [.., S, .., V] (both
        the sequence and vocab extents live in one tensor) — the logits
        (or logits-grad) materialisation the chunked CE removes;
      pallas_calls: how many kernel launches the step contains.
    """
    out = {"attn_transposes": 0, "vocab_intermediates": 0,
           "pallas_calls": 0, "attn_transpose_shapes": [],
           "vocab_shapes": []}
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "pallas_call":
            out["pallas_calls"] += 1
        if name == "transpose":
            aval = eqn.invars[0].aval
            shape = tuple(getattr(aval, "shape", ()))
            dt = str(getattr(aval, "dtype", ""))
            if (len(shape) >= 4 and dt in ("bfloat16", "float16")
                    and seq_len in shape and head_dim in shape):
                out["attn_transposes"] += 1
                out["attn_transpose_shapes"].append(shape)
        for ov in eqn.outvars:
            shape = tuple(getattr(ov.aval, "shape", ()))
            # >=3-D: logits/logit-grads are [B, S, V]; 2-D [V, H] weights
            # (and their grads) are params, not intermediates — at gpt2m
            # hidden_size == seq_len so a 2-D test would false-positive
            if len(shape) >= 3 and vocab_size in shape and seq_len in shape:
                out["vocab_intermediates"] += 1
                if shape not in out["vocab_shapes"]:
                    out["vocab_shapes"].append(shape)
    return out


def trace_train_step(step, inputs, labels):
    """Closed jaxpr of a TrainStep's jitted program at these shapes."""
    import jax.numpy as jnp
    from ..framework import state

    lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
    inputs = inputs if isinstance(inputs, tuple) else (inputs,)
    labels = labels if isinstance(labels, tuple) else (labels,)
    traced = step._compiled.trace(
        step.params, step.buffers, step.opt_state, step.grad_acc,
        state.next_rng_key(), lr, jnp.asarray(1, jnp.int32),
        tuple(jnp.asarray(x) for x in inputs),
        tuple(jnp.asarray(y) for y in labels))
    closed = traced.jaxpr
    # XLA dead-code-eliminates values that never leave the program (the
    # fused-loss models return logits that TrainStep drops); census the
    # DCE'd jaxpr so counts match what actually compiles and runs
    from jax._src.interpreters import partial_eval as pe
    dce, _ = pe.dce_jaxpr(closed.jaxpr, [True] * len(closed.jaxpr.outvars))
    return dce
