"""paddle_tpu.utils (ref python/paddle/utils)."""
def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None
