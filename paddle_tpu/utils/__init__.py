"""paddle_tpu.utils (ref python/paddle/utils)."""
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import telemetry  # noqa: F401  (after monitor/profiler: it uses both)
from . import flight_recorder  # noqa: F401
from . import chaos  # noqa: F401  (after flight_recorder: firings journal)


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check():
    """ref paddle.utils.run_check: sanity-check the install + device."""
    import jax
    import numpy as np
    from .. import to_tensor
    backend = jax.default_backend()
    x = to_tensor(np.ones((2, 2), "f4"))
    y = (x @ x).numpy()
    if float(y[0, 0]) != 2.0:       # not assert: must survive python -O
        raise RuntimeError(
            f"paddle_tpu self-check FAILED on backend {backend}: "
            f"ones(2,2) @ ones(2,2) gave {y!r}, expected 2.0s")
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! backend={backend}, "
          f"{n} device(s) visible.")


def deprecated(update_to="", since="", reason=""):
    """ref paddle.utils.deprecated decorator."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__qualname__}' is deprecated"
            if since:
                msg += f" since {since}"
            if reason:
                msg += f": {reason}"
            if update_to:
                msg += f"; use '{update_to}' instead"
            with warnings.catch_warnings():
                # DeprecationWarning is filtered outside __main__ by
                # default; the reference forces visibility the same way
                warnings.simplefilter("always", DeprecationWarning)
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco
