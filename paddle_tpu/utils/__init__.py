"""paddle_tpu.utils (ref python/paddle/utils)."""
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None
