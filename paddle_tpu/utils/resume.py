"""Exact-resume elastic training: full train-state capture/restore and
the training watchdog.

PR 8 made checkpoint *writes* crash-safe; this module makes a resumed
run the SAME run. A checkpoint that only holds params + optimizer
moments silently changes the loss trajectory on resume — the RNG chain
restarts (different dropout masks), the data cursor resets (batches
replayed or skipped), the LR schedule and AMP loss scale re-derive from
scratch. `capture_train_state`/`apply_train_state` close that gap: the
`.pdtrain` file `hapi.Model.save` writes alongside `.pdparams`/`.pdopt`
(all three digests under one versioned `latest.json` manifest entry)
records

  * the default generator's split-on-demand PRNG chain — the exact key,
    so dropout streams resume mid-epoch bitwise
    (`framework.state.rng_state`);
  * the global numpy RNG — shuffle permutations and numpy transforms
    (`framework.state.numpy_rng_state`);
  * the data cursor: epoch, batches consumed, and the numpy RNG state
    at the START of the in-progress epoch (what `Model.fit`'s
    fast-forward replays so the epoch's shuffle permutation
    reconstructs identically);
  * `amp.GradScaler` scale + good/bad step counters, when a scaler is
    attached to the Model;
  * the global step and the prior run's flight-recorder `run_id`, so
    the resumed journal's `resume` event names what it continues.

The kill/resume parity proof lives in `scripts/chaos_train.py`: kill at
any injected step boundary (`chaos.TRAIN_STEP`), resume via
`Model.load_latest`, and the per-step (loss, grad-norm) trajectory is
bitwise-identical to an uninterrupted seeded run. The
`chaos.TRAIN_STATE` payload point drops keys from the captured state —
the harness's positive controls (`--inject rng-drop`) prove the parity
check actually bites.

`TrainWatchdog` is the hang half of elastic training: a monitor thread
fed a `beat()` per completed step (wired through
`TrainStep.attach_flight_recorder`) that journals a `hang` event with
thread stack dumps when no step lands within a configurable multiple
of the rolling step time, bumps `train_watchdog_stalls_total`, and
optionally interrupts the main thread after a hard deadline — a hung
collective or stuck input pipeline becomes an observable, recoverable
event instead of a silent stall.

Metric catalog entries live in docs/observability.md; the full
robustness story (checkpoint contents table, chaos scenario catalog,
watchdog tuning) in docs/robustness.md.
"""
import sys
import threading
import time
import traceback

from . import telemetry, chaos, flight_recorder
from ..framework import state

#: schema version of the `.pdtrain` payload — bump on incompatible
#: layout changes; `apply_train_state` refuses newer versions rather
#: than resuming with silently-misread state. 2 adds the `sharding`
#: record (mesh shape / dp_axis / zero_stage / per-leaf PartitionSpecs
#: from `ShardedTrainStep.sharding_state`) — readers tolerate its
#: absence, so v1 checkpoints still resume (as unsharded provenance).
STATE_VERSION = 2

_RESUMES = telemetry.counter(
    "train_resumes_total",
    "Training runs resumed from a full-state checkpoint")
_RESHARDS = telemetry.counter(
    "train_reshards_total",
    "Resumes that relaid sharded training state onto a different mesh")
_WATCHDOG_STALLS = telemetry.counter(
    "train_watchdog_stalls_total",
    "Stalled-step episodes detected by the training watchdog")


# ---------------------------------------------------------------------------
# train-state capture / restore
# ---------------------------------------------------------------------------

def capture_train_state(cursor=None, step=None, scaler=None, run_id=None,
                        sharding=None):
    """The full non-(param/optimizer) training state as one picklable
    dict — everything a resumed run needs to continue the EXACT
    trajectory. `cursor` is Model.fit's data cursor
    ({"epoch", "batch", "epoch_numpy_rng"}), `scaler` an optional
    `amp.GradScaler`, `run_id` the writing run's flight-recorder id,
    `sharding` a `ShardedTrainStep.sharding_state()` record (mesh
    shape, dp_axis, zero_stage, per-leaf PartitionSpecs) when the
    writing step was sharded — the provenance an elastic reshard
    journals against (`record_reshard`).

    The `chaos.TRAIN_STATE` payload point may name keys to DROP — the
    parity harness's positive controls (a checkpoint without its RNG
    chain must make the kill/resume parity check fail; one without its
    `sharding` record must fail the reshard-bookkeeping check)."""
    doc = {
        "version": STATE_VERSION,
        "time_unix": round(time.time(), 3),
        "rng": state.rng_state(),
        "numpy_rng": state.numpy_rng_state(),
        "cursor": None if cursor is None else dict(cursor),
        "step": None if step is None else int(step),
        "scaler": None if scaler is None else dict(scaler.state_dict()),
        "run_id": run_id,
        "sharding": None if sharding is None else dict(sharding),
    }
    if chaos.enabled():
        dropped = chaos.value(chaos.TRAIN_STATE, default=())
        for key in tuple(dropped or ()):
            doc.pop(key, None)
    return doc


def apply_train_state(doc, scaler=None):
    """Restore a `capture_train_state` snapshot into the process: RNG
    chains re-wound, scaler state reloaded. Returns the resume info
    `Model.fit(resume=True)` consumes: {"cursor", "step", "run_id"}.
    Missing keys are tolerated (a positive-control checkpoint may have
    dropped them — the parity harness then proves the divergence);
    a NEWER version than this reader understands is refused."""
    if not isinstance(doc, dict):
        raise ValueError(f"train state is not a dict: {type(doc).__name__}")
    version = int(doc.get("version", 0))
    if version > STATE_VERSION:
        raise ValueError(
            f"checkpoint train-state version {version} is newer than this "
            f"reader ({STATE_VERSION}); refusing a silently-partial resume")
    if doc.get("rng") is not None:
        state.set_rng_state(doc["rng"])
    if doc.get("numpy_rng") is not None:
        state.set_numpy_rng_state(doc["numpy_rng"])
    if scaler is not None and doc.get("scaler") is not None:
        scaler.load_state_dict(doc["scaler"])
    return {"cursor": doc.get("cursor"), "step": doc.get("step"),
            "run_id": doc.get("run_id"), "sharding": doc.get("sharding")}


def record_resume(recorder=None, prior_run_id=None, step=None, epoch=None,
                  batch=None):
    """Count a resume (`train_resumes_total`) and journal the `resume`
    event next to the new run's `run_start`."""
    _RESUMES.inc()
    rec = recorder if recorder is not None else flight_recorder.get_recorder()
    if rec is not None:
        rec.resume(prior_run_id=prior_run_id, step=step, epoch=epoch,
                   batch=batch)


def mesh_shape_dict(mesh=None):
    """{axis: size} of `mesh` (default: the installed global mesh), or
    None without one — the comparison key `maybe_record_reshard` uses."""
    from ..distributed import mesh as mesh_mod
    m = mesh_mod.get_mesh() if mesh is None else mesh
    if m is None:
        return None
    return {name: int(m.shape[name]) for name in m.axis_names}


def maybe_record_reshard(resume_info, recorder=None):
    """Elastic-reshard bookkeeping, called by `fit(resume=True)` after
    the `resume` event: when the checkpoint's `.pdtrain` carries a
    `sharding` record and the CURRENT mesh shape differs from the one
    the checkpoint was written on, count `train_reshards_total` and
    journal a `reshard` event (from/to mesh shapes, dp sizes, the
    checkpoint's zero_stage). The actual relayout needs no action here
    — the rebuilt `ShardedTrainStep` re-derives `_zero_spec` placements
    for the current mesh and `device_put`s the restored host state —
    but the transition must be observable, and the sharded parity
    harness's `--inject spec-drop` control (checkpoint stripped of its
    sharding record) is caught exactly because this event then cannot
    name the mesh it came from. Returns the journaled event (or None)."""
    shard_doc = (resume_info or {}).get("sharding") or None
    if not shard_doc or not isinstance(shard_doc, dict):
        return None
    from_mesh = shard_doc.get("mesh") or {}
    to_mesh = mesh_shape_dict()
    if to_mesh is None or dict(from_mesh) == to_mesh:
        return None
    _RESHARDS.inc()
    dp_axis = shard_doc.get("dp_axis")
    rec = recorder if recorder is not None else flight_recorder.get_recorder()
    if rec is None:
        return None
    return rec.reshard(
        from_mesh=dict(from_mesh), to_mesh=to_mesh,
        from_dp=from_mesh.get(dp_axis), to_dp=to_mesh.get(dp_axis),
        zero_stage=shard_doc.get("zero_stage"))


# ---------------------------------------------------------------------------
# training watchdog
# ---------------------------------------------------------------------------

def _thread_stacks(skip_ident=None, limit=25, max_chars=4000):
    """Formatted stacks of every live thread (the hang post-mortem) —
    the watchdog's own monitor thread excluded."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        name = names.get(ident, str(ident))
        text = "".join(traceback.format_stack(frame, limit=limit))
        stacks[name] = text[-max_chars:]
    return stacks


class TrainWatchdog:
    """Stalled-step detector for the training loop.

        wd = TrainWatchdog(stall_factor=10.0, min_stall_s=5.0)
        step.attach_flight_recorder(rec, watchdog=wd)   # or
        model.fit(..., flight_recorder=rec, watchdog=wd)

    `beat(step_s)` is called once per COMPLETED step (TrainStep's
    instrumented path does it); the monitor thread wakes every `poll_s`
    and, when no beat landed within
    `max(min_stall_s, stall_factor * rolling_step_time)`, journals a
    `hang` event (thread stack dumps included) through the recorder and
    bumps `train_watchdog_stalls_total` — once per stall EPISODE, not
    per poll. With `deadline_s` set, a stall older than the deadline
    additionally journals `action="interrupt"` and raises
    KeyboardInterrupt into the main thread (`_thread.interrupt_main`) —
    turning a hard hang into a crash the checkpoint/resume layer
    already survives.

    The rolling step time is an EWMA (`ewma_alpha`), so the threshold
    tracks the run's real cadence instead of a guessed constant. The
    first `warmup_beats` completed steps do NOT feed the EWMA — the
    first step carries the executable compile, and folding a one-off
    multi-second compile into the cadence would leave the threshold
    uselessly slack for the whole run. Until the EWMA is seeded,
    `min_stall_s` alone applies (so it must cover the compile: raise it
    when cold compiles are slow, or `beat()` manually after warmup)."""

    def __init__(self, stall_factor=10.0, min_stall_s=5.0, poll_s=None,
                 deadline_s=None, recorder=None, interrupt=True,
                 on_stall=None, warmup_beats=1):
        self.stall_factor = float(stall_factor)
        self.min_stall_s = float(min_stall_s)
        self.poll_s = max(0.005, float(poll_s) if poll_s is not None
                          else self.min_stall_s / 4.0)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.interrupt = bool(interrupt)
        self.on_stall = on_stall
        self._recorder = recorder
        self.warmup_beats = max(0, int(warmup_beats))
        self._lock = threading.Lock()
        self._beats = 0
        self._ewma = None
        self._ewma_alpha = 0.3
        self._last_beat = None
        self._last_step = None
        self._flagged = False
        self._interrupted = False
        self.stalls = 0
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- wiring
    def start(self):
        """Arm the monitor (idempotent). The stall clock starts NOW."""
        with self._lock:
            self._last_beat = time.monotonic()
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="train-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def reset_warmup(self):
        """Re-enter the EWMA warmup (and restart the stall clock): the
        next `warmup_beats` completed steps do NOT feed the rolling
        step time, and until it re-seeds `min_stall_s` alone applies.
        `fit(resume=True)` calls this on a watchdog that survived into
        the resumed run — the resumed process's first step carries a
        fresh compile (a resharded sharded step ALWAYS recompiles: new
        mesh, new placements), and an EWMA warmed on the pre-kill
        cadence would otherwise read that one-off compile as a stalled
        step and journal a false `hang` episode."""
        with self._lock:
            self._beats = 0
            self._ewma = None
            self._last_beat = time.monotonic()
            self._flagged = False
            self._interrupted = False
        return self

    def beat(self, step_s=None, step=None):
        """One completed train step took `step_s` seconds."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._flagged = False
            self._interrupted = False
            if step is not None:
                self._last_step = int(step)
            self._beats += 1
            if step_s is not None and step_s > 0 \
                    and self._beats > self.warmup_beats:
                a = self._ewma_alpha
                self._ewma = (float(step_s) if self._ewma is None
                              else a * float(step_s) + (1 - a) * self._ewma)

    def threshold_s(self):
        with self._lock:
            if self._ewma is None:
                return self.min_stall_s
            return max(self.min_stall_s, self.stall_factor * self._ewma)

    # ------------------------------------------------------------ monitor
    def _journal_hang(self, age, thr, action):
        rec = self._recorder if self._recorder is not None \
            else flight_recorder.get_recorder()
        if rec is None:
            return
        try:
            rec.hang(age_s=age, threshold_s=thr, step=self._last_step,
                     action=action,
                     stacks=_thread_stacks(skip_ident=threading.get_ident()))
            rec.flush()
        except Exception:  # ptlint: disable=swallowed-exception
            # watchdog-thread contract: a failing journal write (disk
            # full, recorder closed mid-teardown) must never crash the
            # monitor or mask the hang it is reporting
            pass

    def _monitor(self):
        while not self._stop.wait(self.poll_s):
            with self._lock:
                last = self._last_beat
                flagged, interrupted = self._flagged, self._interrupted
            if last is None:
                continue
            age = time.monotonic() - last
            thr = self.threshold_s()
            if age <= thr:
                continue
            if not flagged:
                with self._lock:
                    self._flagged = True
                self.stalls += 1
                _WATCHDOG_STALLS.inc()
                self._journal_hang(age, thr, "observe")
                if self.on_stall is not None:
                    try:
                        self.on_stall(self, age)
                    except Exception:  # ptlint: disable=swallowed-exception
                        # a user stall-callback raising in the monitor
                        # thread would kill the watchdog itself
                        pass
            if self.deadline_s is not None and age > self.deadline_s \
                    and not interrupted:
                with self._lock:
                    self._interrupted = True
                self._journal_hang(age, self.deadline_s, "interrupt")
                if self.interrupt:
                    import _thread
                    _thread.interrupt_main()
