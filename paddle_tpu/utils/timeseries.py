"""In-process metrics time-series plane — fixed-memory history for every
registered metric.

The telemetry registry (utils/telemetry.py) answers "what is the value
now"; this module answers "what did it just do".  A `MetricsSampler`
walks the registry periodically (and on key events — decode-wave end,
fleet step, train step) and pushes every counter/gauge value, plus each
histogram's derived p50/p99, into a per-series `SeriesLadder`:

* tier 0 — the most recent `window` raw samples, full resolution;
* tier 1 — older samples folded `agg_factor` at a time into
  (min, mean, max) buckets, the last `window` buckets kept.

That is the classic RRD two-tier downsampling shape: O(window) memory
per series forever, recent detail intact, older history still showing
envelopes (a spike survives aggregation as a `max` excursion).  **No
banked artifact carries a timestamp** — series are keyed by sample
index, so the history payload is byte-identical across runs that push
identical values (tests pin this), and the wall clock is consulted only
to rate-limit `maybe_sample()`.

The plane is served three ways, all from one payload:

* `telemetry.snapshot_history()` — the JSON-able dict;
* `GET /metrics/history` on any MetricsServer — the same dict, dumped
  with sorted keys (deterministic bytes);
* `GET /dashboard` — one self-contained HTML page of inline-SVG
  sparklines built per request from the same payload (no JS, no
  external assets — curl it from an air-gapped box).

`utils/anomaly.py` consumes the same sampled values for online
anomaly detection; the sampler itself stays judgment-free.
"""

import collections
import html
import math
import threading

from . import telemetry

#: default tier-0 capacity (raw samples) and tier-1 capacity (buckets)
DEFAULT_WINDOW = 120
#: raw samples folded per tier-1 bucket
DEFAULT_AGG_FACTOR = 8

_SAMPLES_TOTAL = telemetry.counter(
    "timeseries_samples_total",
    "Sampling passes the metrics history sampler has taken")


def _finite(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


class SeriesLadder:
    """Two-tier fixed-memory history of one metric series.

    Raw samples land in `recent` (ring of `window`).  A sample evicted
    from `recent` joins a pending fold; every `agg_factor` evictions
    close one (min, mean, max) bucket appended to `agg` (ring of
    `window` buckets — the oldest buckets fall off the end, which is
    the fixed-memory guarantee).  Total state is bounded by
    `window + 3 * window + agg_factor` floats regardless of how many
    samples were ever pushed."""

    __slots__ = ("window", "agg_factor", "recent", "agg", "_pending",
                 "count", "last_index")

    def __init__(self, window=DEFAULT_WINDOW, agg_factor=DEFAULT_AGG_FACTOR):
        self.window = max(1, int(window))
        self.agg_factor = max(1, int(agg_factor))
        self.recent = collections.deque()
        self.agg = collections.deque(maxlen=self.window)
        self._pending = []
        self.count = 0          # samples ever pushed into THIS series
        self.last_index = -1    # sampler pass index of the latest push

    def push(self, value, index):
        if len(self.recent) >= self.window:
            self._pending.append(self.recent.popleft())
            if len(self._pending) >= self.agg_factor:
                p = self._pending
                self.agg.append((min(p), sum(p) / len(p), max(p)))
                self._pending = []
        self.recent.append(float(value))
        self.count += 1
        self.last_index = int(index)

    def point_capacity(self):
        """Float slots this ladder can ever hold (the memory bound the
        tests pin at 10x window)."""
        return self.window + 3 * self.window + self.agg_factor

    def payload(self):
        return {
            "count": self.count,
            "last_index": self.last_index,
            "recent": [telemetry._json_safe(v) for v in self.recent],
            "agg": [[telemetry._json_safe(lo), telemetry._json_safe(mean),
                     telemetry._json_safe(hi)]
                    for lo, mean, hi in self.agg],
        }


def series_key(name, labels=None):
    """Prometheus-flavored series key: `name` or `name{k="v",...}` with
    labels sorted — one canonical spelling per series."""
    if not labels:
        return str(name)
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsSampler:
    """Samples every registered metric into per-series ladders.

    `sample(extra=...)` takes one pass unconditionally; `maybe_sample()`
    rate-limits against `interval_s` on the injected `clock` (event
    hooks — wave end, fleet step, train step — call maybe_sample so an
    idle-spinning loop cannot flood the ladders).  `extra` merges
    caller-provided series (the fleet router passes per-replica queue
    depths there; a retired replica simply stops appearing and its
    ladder freezes without touching any other series' aggregates)."""

    def __init__(self, registry=None, window=DEFAULT_WINDOW,
                 agg_factor=DEFAULT_AGG_FACTOR, interval_s=0.25,
                 clock=None):
        self.registry = registry or telemetry.REGISTRY
        self.window = max(1, int(window))
        self.agg_factor = max(1, int(agg_factor))
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._series = {}
        self._samples = 0
        self._last_t = None

    # ------------------------------------------------------------ sampling
    def maybe_sample(self, extra=None):
        """One pass, unless the last one was under `interval_s` ago.
        Returns True when a pass ran.  With no clock configured and
        interval_s <= 0, every call samples."""
        if self.interval_s > 0:
            clock = self._clock
            if clock is None:
                import time
                clock = time.monotonic
            now = clock()
            if self._last_t is not None and now - self._last_t < \
                    self.interval_s:
                return False
            self._last_t = now
        self.sample(extra=extra)
        return True

    def sample(self, extra=None):
        """One sampling pass: every counter/gauge value, every
        histogram's p50/p99 (skipped until it has observations), plus
        `extra` {series_key: value}.  Non-finite and non-numeric values
        are dropped for that pass — a NaN gauge must not poison a
        bucket's min/mean/max."""
        values = {}
        reg = self.registry
        for name in reg.names():
            m = reg.get(name)
            if m is None:
                continue
            for label_values, child in m._series():
                labels = dict(zip(m.labelnames, label_values))
                if m.kind == "histogram":
                    p50 = child.percentile(50)
                    if p50 is None:
                        continue
                    values[series_key(name + "_p50", labels)] = p50
                    values[series_key(name + "_p99", labels)] = \
                        child.percentile(99)
                else:
                    values[series_key(name, labels)] = child.value()
        for key, v in (extra or {}).items():
            values[str(key)] = v
        with self._lock:
            index = self._samples
            self._samples += 1
            for key in sorted(values):
                v = _finite(values[key])
                if v is None:
                    continue
                ladder = self._series.get(key)
                if ladder is None:
                    ladder = self._series[key] = SeriesLadder(
                        self.window, self.agg_factor)
                ladder.push(v, index)
        _SAMPLES_TOTAL.inc()
        return index

    # ------------------------------------------------------------- readers
    @property
    def samples(self):
        with self._lock:
            return self._samples

    def latest(self, key, default=None):
        with self._lock:
            ladder = self._series.get(key)
            if ladder is None or not ladder.recent:
                return default
            return ladder.recent[-1]

    def history(self):
        """The JSON-able history payload.  Deterministic by
        construction: sorted series keys, sample-index based, no
        timestamps anywhere."""
        with self._lock:
            out = {
                "version": 1,
                "window": self.window,
                "agg_factor": self.agg_factor,
                "samples": self._samples,
                "series": {k: self._series[k].payload()
                           for k in sorted(self._series)},
            }
        return out

    def point_budget(self):
        """Total float slots across every ladder — the live number the
        memory-bound test compares against 10x window per series."""
        with self._lock:
            return sum(l.point_capacity() for l in self._series.values())


def empty_history(window=DEFAULT_WINDOW, agg_factor=DEFAULT_AGG_FACTOR):
    """What /metrics/history serves before any sampler is installed."""
    return {"version": 1, "window": int(window),
            "agg_factor": int(agg_factor), "samples": 0, "series": {}}


# ---------------------------------------------------------------------------
# process-wide sampler slot (telemetry.snapshot_history / the exporter
# endpoints resolve it at call time — newest install wins, mirroring the
# engine's health-probe discipline)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global_sampler = None


def install_sampler(sampler):
    """Make `sampler` the process-wide history source (served by every
    MetricsServer's /metrics/history + /dashboard and by
    telemetry.snapshot_history).  Returns the sampler."""
    global _global_sampler
    with _global_lock:
        _global_sampler = sampler
    return sampler


def get_sampler():
    with _global_lock:
        return _global_sampler


def uninstall_sampler(sampler=None):
    """Remove the installed sampler (or only `sampler`, if it is still
    the installed one — a test tearing down must not evict a newer
    install)."""
    global _global_sampler
    with _global_lock:
        if sampler is None or _global_sampler is sampler:
            _global_sampler = None


# ---------------------------------------------------------------------------
# /dashboard — one self-contained page of sparklines
# ---------------------------------------------------------------------------

_SPARK_W, _SPARK_H = 240, 36


def _spark_svg(points, band=None):
    """Inline-SVG sparkline: `points` polyline, optional (lo, hi) band
    behind it (the aggregated tier's min/max envelope)."""
    if not points:
        return "<svg width='%d' height='%d'></svg>" % (_SPARK_W, _SPARK_H)
    everything = list(points)
    if band:
        everything += [v for lo, hi in band for v in (lo, hi)]
    vmin, vmax = min(everything), max(everything)
    span = (vmax - vmin) or 1.0
    n = max(len(points) + (len(band or ())), 2)

    def x(i):
        return round(i * (_SPARK_W - 2) / (n - 1) + 1, 2)

    def y(v):
        return round(_SPARK_H - 2 - (v - vmin) * (_SPARK_H - 4) / span, 2)

    parts = [f"<svg width='{_SPARK_W}' height='{_SPARK_H}' "
             f"viewBox='0 0 {_SPARK_W} {_SPARK_H}'>"]
    if band:
        top = " ".join(f"{x(i)},{y(hi)}" for i, (_, hi) in enumerate(band))
        bot = " ".join(f"{x(i)},{y(lo)}"
                       for i, (lo, _) in reversed(list(enumerate(band))))
        parts.append(f"<polygon points='{top} {bot}' fill='#cfe3f7' "
                     "stroke='none'/>")
    offset = len(band or ())
    line = " ".join(f"{x(offset + i)},{y(v)}"
                    for i, v in enumerate(points))
    parts.append(f"<polyline points='{line}' fill='none' "
                 "stroke='#1f6fb2' stroke-width='1.5'/>")
    parts.append("</svg>")
    return "".join(parts)


def render_dashboard(history, title="paddle_tpu metrics"):
    """One self-contained HTML page (no JS, no external assets): a row
    per series — aggregated min/max envelope + mean, then the raw
    recent tail, latest value on the right.  Built per request from the
    history payload, so it is exactly as fresh as the last sample."""
    rows = []
    for key in sorted(history.get("series", {})):
        s = history["series"][key]
        band = [(lo, hi) for lo, _, hi in s.get("agg", ())
                if _finite(lo) is not None and _finite(hi) is not None]
        means = [m for _, m, _ in s.get("agg", ())
                 if _finite(m) is not None]
        recent = [v for v in s.get("recent", ())
                  if _finite(v) is not None]
        latest = recent[-1] if recent else (means[-1] if means else None)
        latest_s = "—" if latest is None else f"{latest:.6g}"
        rows.append(
            "<tr><td class='k'>%s</td><td>%s</td>"
            "<td class='v'>%s</td><td class='n'>%d</td></tr>"
            % (html.escape(key), _spark_svg(means + recent, band=band),
               latest_s, s.get("count", 0)))
    body = "\n".join(rows) or \
        "<tr><td colspan='4'>no samples yet</td></tr>"
    return f"""<!doctype html>
<html><head><meta charset="utf-8"><title>{html.escape(title)}</title>
<style>
 body {{ font: 13px/1.4 system-ui, sans-serif; margin: 1.5em; }}
 table {{ border-collapse: collapse; }}
 td {{ padding: 2px 10px; border-bottom: 1px solid #eee;
      vertical-align: middle; }}
 td.k {{ font-family: ui-monospace, monospace; }}
 td.v {{ text-align: right; font-variant-numeric: tabular-nums; }}
 td.n {{ color: #888; text-align: right; }}
</style></head><body>
<h1>{html.escape(title)}</h1>
<p>{history.get("samples", 0)} sampling passes ·
window {history.get("window")} raw + {history.get("window")}
aggregated buckets × {history.get("agg_factor")} samples
(band = aggregated min/max envelope, line = mean then raw tail)</p>
<table><tr><th>series</th><th>history</th><th>latest</th>
<th>samples</th></tr>
{body}
</table></body></html>
"""
