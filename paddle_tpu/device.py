"""paddle.device namespace (ref python/paddle/device.py)."""
from .framework.state import set_device, get_device


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_tpu():
    return True


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_device_count():
    import jax
    return len(jax.devices())


class cuda:       # paddle.device.cuda namespace shim
    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False
