"""SelectedRows — row-sparse gradients for embedding tables
(ref paddle/fluid/framework/selected_rows.h + operators/sum_op sparse
accumulation + optimizers' SelectedRows kernels, e.g. sgd_op.h
SparseSGDFunctor).

A SelectedRows holds (rows, values[len(rows), dim], height): the gradient
of an embedding lookup touches only the looked-up rows, so eager backward
can carry O(batch * dim) instead of O(vocab * dim). On TPU the compiled
training path doesn't need this (XLA fuses the scatter-add into the
update), but the EAGER path and the PS path (push_sparse_grad) do — this
is the dygraph `.grad` format for Embedding(sparse=True), exactly like the
reference's VarBase holding a SelectedRows."""
import numpy as np
import jax
import jax.numpy as jnp


class SelectedRows:
    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows, jnp.int32).ravel()
        self.values = jnp.asarray(values)
        self.height = int(height)
        assert self.values.shape[0] == self.rows.shape[0], \
            (self.values.shape, self.rows.shape)

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self):
        """Deduplicate rows, summing their values (ref
        operators/math/selected_rows_functor.cc MergeAdd)."""
        rows = np.asarray(self.rows)
        uniq, inv = np.unique(rows, return_inverse=True)
        if uniq.size == rows.size:
            return self
        summed = jnp.zeros((uniq.size,) + self.values.shape[1:],
                           self.values.dtype).at[jnp.asarray(inv)] \
            .add(self.values)
        return SelectedRows(uniq, summed, self.height)

    def to_dense(self):
        out = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                        self.values.dtype)
        return out.at[self.rows].add(self.values)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype),
                            self.height)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            assert other.height == self.height
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values,
                                 other.values.astype(self.values.dtype)]),
                self.height)
        # dense + sparse -> dense
        return self.to_dense() + other

    __radd__ = __add__

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"dim={tuple(self.values.shape[1:])})")
