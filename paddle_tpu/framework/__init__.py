from . import jax_compat  # noqa: F401  (must run before any jax.typeof use)
from .dtype import (float16, bfloat16, float32, float64, int8, int16, int32,
                    int64, uint8, bool_, complex64, complex128, convert_dtype,
                    dtype_name, is_floating_point, is_integer)
from .state import (Place, CPUPlace, TPUPlace, CUDAPlace, XPUPlace, set_device,
                    get_device, get_place, seed, default_generator, next_rng_key,
                    set_flags, get_flags, get_flag, no_grad, no_grad_ctx,
                    enable_grad_ctx, functional_mode_ctx, is_grad_enabled,
                    is_functional_mode, set_default_dtype, get_default_dtype)
from .tensor import Tensor, Parameter, to_tensor
from . import tape
from . import errors
from .errors import enforce, enforce_eq, enforce_shape


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Top-level paddle.create_parameter (ref python/paddle/__init__.py:237
    alias of fluid framework.create_parameter): a fresh trainable Parameter,
    Xavier-normal by default, zeros when is_bias."""
    from ..nn import initializer as I
    init = default_initializer
    if init is None and attr is not None and getattr(attr, "initializer",
                                                    None) is not None:
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    p = Parameter(init(tuple(shape), dtype),
                  name=name or (getattr(attr, "name", None) if attr else None))
    if attr is not None and getattr(attr, "regularizer", None) is not None:
        p.regularizer = attr.regularizer
    return p
