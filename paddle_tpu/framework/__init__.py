from .dtype import (float16, bfloat16, float32, float64, int8, int16, int32,
                    int64, uint8, bool_, complex64, complex128, convert_dtype,
                    dtype_name, is_floating_point, is_integer)
from .state import (Place, CPUPlace, TPUPlace, CUDAPlace, XPUPlace, set_device,
                    get_device, get_place, seed, default_generator, next_rng_key,
                    set_flags, get_flags, get_flag, no_grad, no_grad_ctx,
                    enable_grad_ctx, functional_mode_ctx, is_grad_enabled,
                    is_functional_mode, set_default_dtype, get_default_dtype)
from .tensor import Tensor, Parameter, to_tensor
from . import tape
from . import errors
from .errors import enforce, enforce_eq, enforce_shape
