"""Eager autograd engine (dygraph `.backward()`).

TPU-native redesign of the reference imperative engine
(ref paddle/fluid/imperative/basic_engine.cc:39,265 BasicEngine::Init/Execute and
gradient_accumulator.cc): instead of GradOpNode objects created from a C++ grad-op
registry, every eager op records a GradNode whose `vjp` closure comes from jax.vjp of
the op's pure-JAX implementation — the VJP itself is XLA-compiled, so the backward
hot loop is one cached executable launch per op, mirroring the reference's
one-C++-crossing-per-op design (ref pybind/op_function_generator.cc:488).

Graph lifetime is reference-counted through the output tensors (a node lives as long
as some tensor produced by it), matching dygraph semantics where dropping activations
frees the graph. `backward()` runs a pending-count topological sweep like
BasicEngine::Execute's ready queue.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import state


class GradNode:
    """One recorded op. Outputs hold (node, slot) so multi-output ops share a node."""

    __slots__ = ("vjp", "inputs", "n_outputs", "out_shapes", "out_dtypes", "name",
                 "fn", "primals", "__weakref__")

    def __init__(self, vjp, inputs, n_outputs, out_shapes, out_dtypes, name="",
                 fn=None, primals=None):
        self.vjp = vjp                  # callable: tuple(cotangents) -> tuple(in grads)
        self.inputs = inputs            # list[Tensor | None]; None = non-diff input
        self.n_outputs = n_outputs
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.name = name
        # create_graph (double-grad) support: the bound forward impl + its
        # primal arrays let the backward be REPLAYED through the dispatcher
        # as a taped op, so second-order grads flow (partial_grad_engine
        # analog). None for custom nodes that opt out.
        self.fn = fn
        self.primals = primals


def _is_float0(g):
    return g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0)


_FREED = object()   # sentinel: graph released by a retain_graph=False sweep


def _taped_vjp(node, cot_tensors):
    """Replay one node's backward THROUGH the dispatcher so the computed
    grads carry their own tape (create_graph mode). Returns Tensors."""
    import jax as _jax
    from .tensor import Tensor
    from ..ops import dispatch as _dispatch
    if node.primals is _FREED:
        raise RuntimeError(
            f"create_graph: op '{node.name}' graph was already freed by a "
            "retain_graph=False backward; recompute the forward or pass "
            "retain_graph=True")
    if node.fn is None or node.primals is None:
        raise RuntimeError(
            f"create_graph: op '{node.name}' does not support double "
            "backward (no replayable forward recorded)")
    k = node.n_outputs

    def bwd_fn(*args):
        cots, prims = args[:k], args[k:]
        _, vjp_fn = _jax.vjp(node.fn, *prims)
        gs = vjp_fn(tuple(cots) if k > 1 else cots[0])
        return tuple(gs) if len(gs) > 1 else gs[0]

    prim_tensors = [
        inp if inp is not None else Tensor(p, stop_gradient=True)
        for inp, p in zip(node.inputs, node.primals)]
    out = _dispatch.apply(bwd_fn, tuple(cot_tensors) + tuple(prim_tensors),
                          name=f"{node.name}_grad")
    return out if isinstance(out, tuple) else (out,)


def backward(tensor, grad_tensor=None, retain_graph=False,
             create_graph=False, only_accumulate=None):
    """Reverse sweep from `tensor`. Accumulates into leaf `.grad` (paddle semantics:
    grads accumulate across backward calls until clear_grad). With
    create_graph=True the sweep runs in Tensor space via the dispatcher,
    so the produced grads are themselves differentiable.
    `only_accumulate` (a set of tensor ids) restricts leaf accumulation to
    those tensors — paddle.grad's only_inputs semantics: other leaves'
    .grad slots are left untouched."""
    from .tensor import Tensor

    root_node = tensor._node
    if grad_tensor is None:
        if tensor._data.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad_tensor")
        seed_grad = jnp.ones_like(tensor._data)
    else:
        seed_grad = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    if create_graph:
        retain_graph = True
        if isinstance(grad_tensor, Tensor):
            seed_grad = grad_tensor      # keep the caller's graph
        else:
            seed_grad = Tensor(seed_grad, stop_gradient=True)

    if root_node is None:
        if not tensor.stop_gradient and (
                only_accumulate is None or id(tensor) in only_accumulate):
            _accumulate_leaf(tensor, seed_grad, keep_graph=create_graph)
        return

    # ---- pass 1: count consumer edges per node (DFS over the creator graph)
    pending = {}          # id(node) -> number of consumer edges not yet satisfied
    nodes = {}            # id(node) -> node (keep alive during sweep)
    stack = [root_node]
    nodes[id(root_node)] = root_node
    pending[id(root_node)] = 0
    while stack:
        node = stack.pop()
        for inp in node.inputs:
            if inp is None or inp.stop_gradient:
                continue
            child = inp._node
            if child is None:
                continue
            cid = id(child)
            if cid not in pending:
                pending[cid] = 0
                nodes[cid] = child
                stack.append(child)
            pending[cid] += 1

    # ---- pass 2: ready-queue sweep (ref basic_engine.cc:265)
    # cotangent buckets per node output slot
    cots = {id(root_node): [None] * root_node.n_outputs}
    cots[id(root_node)][tensor._slot] = seed_grad
    ready = [root_node]
    visited_nodes = []
    while ready:
        node = ready.pop()
        visited_nodes.append(node)
        nid = id(node)
        slot_cots = cots.pop(nid)
        if create_graph:
            full_cots = tuple(
                c if c is not None else Tensor(jnp.zeros(s, d),
                                               stop_gradient=True)
                for c, s, d in zip(slot_cots, node.out_shapes,
                                   node.out_dtypes))
            in_grads = _taped_vjp(node, full_cots)
        else:
            full_cots = tuple(
                c if c is not None else jnp.zeros(s, d)
                for c, s, d in zip(slot_cots, node.out_shapes, node.out_dtypes))
            in_grads = node.vjp(full_cots if node.n_outputs > 1 else full_cots[0])
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for inp, g in zip(node.inputs, in_grads):
            garr = g._data if isinstance(g, Tensor) else g
            if inp is None or inp.stop_gradient or _is_float0(garr):
                continue
            child = inp._node
            if child is None:
                if only_accumulate is None or id(inp) in only_accumulate:
                    _accumulate_leaf(inp, g, keep_graph=create_graph)
                continue
            cid = id(child)
            if cid not in pending:      # reached via a path pruned in pass 1
                continue
            bucket = cots.setdefault(cid, [None] * child.n_outputs)
            slot = inp._slot
            bucket[slot] = g if bucket[slot] is None else bucket[slot] + g
            pending[cid] -= 1
            if pending[cid] == 0:
                ready.append(child)

    if not retain_graph:
        for node in visited_nodes:
            node.vjp = None
            node.inputs = ()
            node.fn = None          # release the primal arrays too
            node.primals = _FREED
        # detach root so a second backward errors out cleanly
        tensor._node = None


def _accumulate_leaf(t, g, keep_graph=False):
    from .tensor import Tensor
    from .selected_rows import SelectedRows
    if keep_graph and isinstance(g, Tensor):
        # create_graph mode: grads keep their tape (differentiable)
        t.grad = g if t.grad is None else t.grad + g
        return
    if isinstance(g, Tensor):
        g = g._data
    if g.dtype != t._data.dtype:
        g = g.astype(t._data.dtype)
    if isinstance(g, SelectedRows):
        # row-sparse accumulation (ref gradient_accumulator.cc SelectedRows
        # branch): sparse+sparse concatenates, sparse+dense densifies
        if t.grad is None:
            t.grad = g
        elif isinstance(t.grad, SelectedRows):
            t.grad = t.grad + g
        else:
            t.grad = Tensor(t.grad._data + g.to_dense(), stop_gradient=True)
        return
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    elif isinstance(t.grad, SelectedRows):
        t.grad = Tensor(t.grad.to_dense() + g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._data + g, stop_gradient=True)
