"""Process-global framework state: device/place, RNG, flags, grad & functional modes.

TPU-native redesign of the reference's process-wide services:
  - Place taxonomy + DeviceContextPool (ref paddle/fluid/platform/place.h,
    device_context.h:691) -> a current-Place holder; JAX/PJRT owns streams.
  - gflags FLAGS_* (ref platform/flags.cc) -> a plain dict with set_flags/get_flags.
  - Generator RNG (ref framework/generator.h:93) -> a split-on-demand JAX PRNG key chain.
Grad mode (no_grad) and functional mode (tracing under jax.jit/jax.grad, where the
tape must NOT record) are contextvars so they compose with threads.
"""
import contextlib
import contextvars
import threading

import jax
import numpy as np

from .dtype import float32, convert_dtype

# --------------------------------------------------------------------------- places


class Place:
    """Device placement descriptor. TPU-native: maps onto a jax.Device."""

    def __init__(self, kind: str, device_id: int = 0):
        self.kind = kind
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self.kind == other.kind
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.kind, self.device_id))

    def jax_device(self):
        # local_devices: in a multi-process job "device 0" must mean THIS
        # process's first device — global jax.devices()[0] belongs to rank 0
        # and is not addressable from other ranks
        devs = [d for d in jax.local_devices()
                if _platform_of(d) == self.kind]
        if not devs:  # fall back to host
            devs = [d for d in jax.local_devices()
                    if _platform_of(d) == "cpu"] or jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_tpu_place(self):
        return self.kind != "cpu"

    # reference-API aliases
    is_gpu_place = is_tpu_place


def _platform_of(d):
    p = d.platform
    # axon tunnels expose the real TPU under an experimental platform name
    return "tpu" if p in ("tpu", "axon") else p


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id=0):
    return Place("tpu", device_id)


# Reference compat: CUDAPlace scripts run on the accelerator place.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


class _GlobalState(threading.local):
    pass


_state = _GlobalState()


def _detect_default_place():
    for d in jax.local_devices():
        if _platform_of(d) != "cpu":
            return Place(_platform_of(d), 0)
    return Place("cpu", 0)


_current_place = None
_default_dtype = float32


def set_device(device):
    """paddle.set_device analog: 'cpu', 'tpu', 'tpu:0', 'gpu:0' (alias of tpu)."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    device = str(device)
    if ":" in device:
        kind, idx = device.split(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("gpu", "cuda", "xpu", "npu", "tpu"):
        kind = "tpu"
    _current_place = Place(kind, idx)
    return _current_place


def get_device():
    p = get_place()
    return f"{p.kind}:{p.device_id}"


def get_place():
    global _current_place
    if _current_place is None:
        _current_place = _detect_default_place()
    return _current_place


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


# --------------------------------------------------------------------------- RNG


def host_device():
    """THIS process's host CPU jax device — cheap bookkeeping (PRNG splits,
    init) runs here; on tunneled TPUs every eager dispatch is a network
    round-trip. local_devices, not devices: in a multi-process job the
    global cpu[0] belongs to rank 0 and is unaddressable elsewhere."""
    return jax.local_devices(backend="cpu")[0]


class Generator:
    """Split-on-demand PRNG chain (ref framework/generator.h:93 kept functional:
    every draw advances the chain by splitting, so eager ops stay reproducible).
    Key management happens on host CPU — a split is 8 bytes of work and must
    not pay a device round-trip."""

    def __init__(self, seed=0):
        # Lazy: no JAX backend is touched until the first draw. Importing the
        # framework must never initialize a device (ref initializes devices
        # explicitly from bootstrap, platform/init.h:36 — not at link time);
        # a flaky TPU plugin must not make the package unimportable.
        self._seed = seed
        self._lock = threading.Lock()
        self._key = None

    def manual_seed(self, seed):
        with self._lock:
            self._seed = seed
            self._key = None
        return self

    def next_key(self):
        with self._lock:
            with jax.default_device(host_device()):
                if self._key is None:
                    self._key = jax.random.PRNGKey(self._seed)
                self._key, sub = jax.random.split(self._key)
            return sub

    @property
    def initial_seed(self):
        return self._seed


_default_generator = Generator(0)


def rng_state():
    """Snapshot of the default generator's split-on-demand chain — the
    EXACT point the chain is at, not just the seed. `key` is a host
    numpy copy of the current chain key (None before the first draw):
    restoring it via `set_rng_state` makes the next `next_rng_key()`
    return bitwise what an uninterrupted process would have drawn — the
    contract exact-resume checkpoints (utils/resume.py) rely on for
    dropout streams."""
    g = _default_generator
    with g._lock:
        key = None if g._key is None else np.asarray(g._key).copy()
        return {"seed": int(g._seed), "key": key}


def set_rng_state(st):
    """Restore a `rng_state()` snapshot into the default generator."""
    g = _default_generator
    with g._lock:
        if "seed" in st and st["seed"] is not None:
            g._seed = int(st["seed"])
        key = st.get("key")
        if key is None:
            g._key = None
        else:
            # uncommitted, exactly like Generator.next_key creates keys:
            # a device_put here would COMMIT the key, committedness
            # propagates through the compiled step to its outputs, and
            # the second post-resume call would cache-miss — one silent
            # recompile per resume (chaos_train's compile-once check
            # catches this)
            with jax.default_device(host_device()):
                g._key = jax.numpy.asarray(np.asarray(key))


def numpy_rng_state():
    """The global numpy RNG (MT19937) state as a picklable dict — the
    data-order half of exact resume: DataLoader shuffle permutations
    and per-item numpy transforms draw from it. Checkpoints record both
    the CURRENT state and the state at the start of the in-progress
    epoch (the latter is what a resume fast-forward replays)."""
    alg, keys, pos, has_gauss, cached = np.random.get_state()
    return {"alg": str(alg), "keys": np.asarray(keys).copy(),
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached)}


def set_numpy_rng_state(st):
    """Restore a `numpy_rng_state()` snapshot into the global numpy RNG."""
    np.random.set_state((st["alg"], np.asarray(st["keys"]), int(st["pos"]),
                         int(st["has_gauss"]), float(st["cached_gaussian"])))


def seed(s):
    """paddle.seed analog."""
    _default_generator.manual_seed(int(s))
    np.random.seed(int(s) % (2 ** 32))
    return _default_generator


def default_generator():
    return _default_generator


def next_rng_key():
    traced = _functional_rng.get()
    if traced is not None:
        return traced.next_key()
    return _default_generator.next_key()


# --------------------------------------------------------------------------- flags

_FLAGS = {
    "FLAGS_check_nan_inf": False,           # ref platform/flags.cc:44
    "FLAGS_unused_var_check": False,        # ref framework/unused_var_check.cc
    "FLAGS_sort_sum_gradient": False,       # ref platform/flags.cc:527
    "FLAGS_cudnn_deterministic": True,      # XLA is deterministic by default
    "FLAGS_matmul_precision": "default",    # TPU knob: default|high|highest
    "FLAGS_eager_op_cache": True,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_use_donated_buffers": True,
}


def _bootstrap_env_flags():
    """Parse FLAGS_* env vars at import (ref python/paddle/fluid/__init__.py
    __bootstrap__ passing env gflags to core.init_gflags)."""
    import os
    for key, default in list(_FLAGS.items()):
        raw = os.environ.get(key)
        if raw is None:
            continue
        try:
            if isinstance(default, bool):
                _FLAGS[key] = raw.lower() in ("1", "true", "yes", "on")
            elif isinstance(default, int):
                _FLAGS[key] = int(raw)
            elif isinstance(default, float):
                _FLAGS[key] = float(raw)
            else:
                _FLAGS[key] = raw
        except ValueError:
            import warnings
            warnings.warn(
                f"ignoring malformed env var {key}={raw!r}; keeping "
                f"default {default!r}")


_bootstrap_env_flags()


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flags(keys=None):
    if keys is None:
        return dict(_FLAGS)
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def get_flag(key, default=None):
    return _FLAGS.get(key, default)


# --------------------------------------------------------------------------- modes

_grad_enabled = contextvars.ContextVar("grad_enabled", default=True)
_functional_mode = contextvars.ContextVar("functional_mode", default=False)
_functional_rng = contextvars.ContextVar("functional_rng", default=None)
_static_recorder = contextvars.ContextVar("static_recorder", default=None)


def get_static_recorder():
    """Active ProgramDesc recorder (static/program.py) or None. When set,
    ops/dispatch.apply records every op into the current Program's desc
    (ref imperative/tracer.cc:132 TraceOp writing OpDesc in static mode)."""
    return _static_recorder.get()


@contextlib.contextmanager
def static_recorder_ctx(rec):
    tok = _static_recorder.set(rec)
    try:
        yield
    finally:
        _static_recorder.reset(tok)


class _TracedRng:
    """Split-on-demand chain over a traced PRNG key — lets dropout etc. draw
    fresh randomness inside jit'd train steps (the key is a step input, so each
    compiled step gets a new mask instead of a baked-in constant)."""

    def __init__(self, key):
        self._key = key

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub


@contextlib.contextmanager
def functional_rng_ctx(key):
    tok = _functional_rng.set(_TracedRng(key))
    try:
        yield
    finally:
        _functional_rng.reset(tok)


def is_grad_enabled():
    return _grad_enabled.get()


def is_functional_mode():
    return _functional_mode.get()


@contextlib.contextmanager
def no_grad_ctx():
    tok = _grad_enabled.set(False)
    try:
        yield
    finally:
        _grad_enabled.reset(tok)


@contextlib.contextmanager
def enable_grad_ctx():
    tok = _grad_enabled.set(True)
    try:
        yield
    finally:
        _grad_enabled.reset(tok)


@contextlib.contextmanager
def functional_mode_ctx():
    """Active while tracing a pure function under jax.jit/grad: the eager tape is
    bypassed and autodiff is delegated to JAX (the performance path)."""
    tok = _functional_mode.set(True)
    try:
        yield
    finally:
        _functional_mode.reset(tok)


_amp_state = contextvars.ContextVar("amp_state", default=None)


def get_amp_state():
    return _amp_state.get()


@contextlib.contextmanager
def amp_guard_ctx(cfg):
    tok = _amp_state.set(cfg)
    try:
        yield
    finally:
        _amp_state.reset(tok)


class no_grad:
    """Usable as decorator and context manager, like paddle.no_grad."""

    def __enter__(self):
        self._tok = _grad_enabled.set(False)
        return self

    def __exit__(self, *exc):
        _grad_enabled.reset(self._tok)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad_ctx():
                return fn(*a, **k)

        return wrapper

