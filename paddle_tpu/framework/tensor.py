"""paddle_tpu.Tensor — eager tensor wrapping a jax.Array.

TPU-native analog of the reference's imperative VarBase (ref
paddle/fluid/imperative/layer.h:65) + LoDTensor storage (ref
paddle/fluid/framework/tensor.h:89): device memory is owned by PJRT (no custom
allocator needed — ref memory/allocation/allocator_facade.h becomes the PJRT
arena), autograd linkage is (`_node`, `_slot`) into the tape (tape.py).

Ragged LoDTensor has no XLA-friendly equivalent; sequence ops take dense
padded tensors + length masks instead (see ops/sequence.py).

Arithmetic dunders are attached by paddle_tpu.ops at import time to avoid a
circular import (the reference does the same via generated `core.ops` methods,
pybind/op_function_generator.cc:488).
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import state
from .dtype import convert_dtype, dtype_name


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_slot", "name",
                 "persistable", "trainable", "_hooks", "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            dt = convert_dtype(dtype)
            arr = np.asarray(data)
            if dt is None and arr.dtype == np.float64:
                dt = state.get_default_dtype()
            data = jnp.asarray(arr, dtype=dt)
        elif dtype is not None and data.dtype != convert_dtype(dtype):
            data = data.astype(convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._slot = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks = None

    # ------------------------------------------------------------- properties
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        return state.get_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from ..ops import manipulation
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    def dim(self):
        return self._data.ndim

    def rank(self):
        return self._data.ndim

    def numel(self):
        return self.size

    # ------------------------------------------------------------- conversion
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        from ..ops import manipulation
        return manipulation.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs):
        # device moves are PJRT-managed; only dtype conversion is meaningful here
        for a in args:
            if isinstance(a, (str, np.dtype)) and str(a) not in ("cpu", "tpu", "gpu"):
                try:
                    return self.astype(a)
                except ValueError:
                    pass
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            return self.astype(kwargs["dtype"])
        return self

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import tape
        tape.backward(self, grad_tensor=grad_tensor, retain_graph=retain_graph)

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from ..ops import math as _m
        return _m.assign(self)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Remover:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Remover(self._hooks, hook)

    @property
    def gradient(self):
        if self.grad is None:
            return None
        from .selected_rows import SelectedRows
        if isinstance(self.grad, SelectedRows):
            return np.asarray(self.grad.to_dense())
        return self.grad.numpy()

    # ------------------------------------------------------------- in-place-ish
    def set_value(self, value):
        """In-place value replacement (optimizer updates, state loading)."""
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, v):
        self._data = jnp.full_like(self._data, v)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def scale_(self, v):
        self._data = self._data * v
        return self

    def add_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data + jnp.asarray(o, dtype=self._data.dtype)
        return self

    # ------------------------------------------------------------- indexing
    def __getitem__(self, idx):
        from ..ops import manipulation
        return manipulation.getitem(self, idx)

    def __setitem__(self, idx, value):
        if isinstance(idx, Tensor):
            idx = idx._data
        if isinstance(value, Tensor):
            value = value._data
        self._data = self._data.at[idx].set(value)

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------- misc
    def __repr__(self):
        grad_txt = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={dtype_name(self.dtype)}"
                f"{grad_txt},\n       {np.asarray(self._data)!r})")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor analog."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """Trainable leaf (ref python/paddle/fluid/framework.py:5416 ParamBase)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
