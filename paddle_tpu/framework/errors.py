"""Typed errors + enforce helpers.

TPU-native analog of the reference error machinery
(ref paddle/fluid/platform/enforce.h PADDLE_ENFORCE*, platform/errors.h,
platform/error_codes.proto): the same typed taxonomy, expressed as python
exception classes (no C++ stack demangling needed — python tracebacks carry
the op call stack the reference reconstructs via framework/op_call_stack.cc).
"""


class PaddleTpuError(Exception):
    code = "LEGACY"


class InvalidArgumentError(PaddleTpuError, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(PaddleTpuError, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(PaddleTpuError, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(PaddleTpuError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(PaddleTpuError, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(PaddleTpuError, RuntimeError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(PaddleTpuError, PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(PaddleTpuError, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(PaddleTpuError, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(PaddleTpuError, RuntimeError):
    code = "UNAVAILABLE"


class FatalError(PaddleTpuError, RuntimeError):
    code = "FATAL"


class ExternalError(PaddleTpuError, RuntimeError):
    code = "EXTERNAL"


def enforce(condition, message="", error_cls=PreconditionNotMetError):
    """ref PADDLE_ENFORCE (enforce.h). Raise typed error when false."""
    if not condition:
        raise error_cls(message)


def _short_spec(a):
    dt = getattr(a, "dtype", None)
    sh = getattr(a, "shape", None)
    if dt is None or sh is None:
        return type(a).__name__
    return f"{dt}[{','.join(str(s) for s in sh)}]"


def attach_op_context(exc, op_name, arrays=(), attrs=None, callstack=None):
    """ref framework/op_call_stack.cc InsertCallStackInfo + enforce.h's
    "Error Message Summary": append the failing operator's name, input
    specs, attrs, and (for desc replay) the python call stack recorded at
    op-creation time to the exception message IN PLACE — the type is
    preserved so existing `except ValueError` handlers keep working."""
    lines = [f"  [operator < {op_name} > error]"]
    if arrays:
        lines.append("  [inputs: "
                     + ", ".join(_short_spec(a) for a in arrays) + "]")
    if attrs:
        shown = {k: v for k, v in attrs.items() if not k.startswith("__")}
        if shown:
            lines.append(f"  [attrs: {shown}]")
    if callstack:
        lines.append("  [python call stack (op creation)]:")
        lines += [f"    {fr}" for fr in callstack]
    ctx = "\n".join(lines)
    msg = str(exc.args[0]) if exc.args else ""
    try:
        exc.args = (f"{msg}\n{ctx}",) + tuple(exc.args[1:])
    except (AttributeError, TypeError):
        pass        # exotic exception with immutable args: keep original
    return exc


def user_callstack(limit=5):
    """Non-framework frames of the current python stack, innermost last
    (the reference records these at op-definition time for static graphs
    so runtime failures point at model code, not executor internals)."""
    import traceback
    import os
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for fr in traceback.extract_stack()[:-1]:
        if fr.filename.startswith(pkg):
            continue
        out.append(f"{fr.filename}:{fr.lineno} in {fr.name}: "
                   f"{(fr.line or '').strip()}")
    return out[-limit:]


def enforce_eq(a, b, message="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {message}")


def enforce_shape(tensor, expected, message=""):
    got = tuple(tensor.shape)
    want = tuple(expected)
    ok = len(got) == len(want) and all(
        w in (-1, None) or g == w for g, w in zip(got, want))
    if not ok:
        raise InvalidArgumentError(
            f"shape mismatch: got {got}, expected {want}. {message}")
