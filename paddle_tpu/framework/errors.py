"""Typed errors + enforce helpers.

TPU-native analog of the reference error machinery
(ref paddle/fluid/platform/enforce.h PADDLE_ENFORCE*, platform/errors.h,
platform/error_codes.proto): the same typed taxonomy, expressed as python
exception classes (no C++ stack demangling needed — python tracebacks carry
the op call stack the reference reconstructs via framework/op_call_stack.cc).
"""


class PaddleTpuError(Exception):
    code = "LEGACY"


class InvalidArgumentError(PaddleTpuError, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(PaddleTpuError, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(PaddleTpuError, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(PaddleTpuError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(PaddleTpuError, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(PaddleTpuError, RuntimeError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(PaddleTpuError, PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(PaddleTpuError, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(PaddleTpuError, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(PaddleTpuError, RuntimeError):
    code = "UNAVAILABLE"


class FatalError(PaddleTpuError, RuntimeError):
    code = "FATAL"


class ExternalError(PaddleTpuError, RuntimeError):
    code = "EXTERNAL"


def enforce(condition, message="", error_cls=PreconditionNotMetError):
    """ref PADDLE_ENFORCE (enforce.h). Raise typed error when false."""
    if not condition:
        raise error_cls(message)


def enforce_eq(a, b, message="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {message}")


def enforce_shape(tensor, expected, message=""):
    got = tuple(tensor.shape)
    want = tuple(expected)
    ok = len(got) == len(want) and all(
        w in (-1, None) or g == w for g, w in zip(got, want))
    if not ok:
        raise InvalidArgumentError(
            f"shape mismatch: got {got}, expected {want}. {message}")
