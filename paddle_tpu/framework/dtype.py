"""Dtype taxonomy for paddle_tpu.

TPU-first: bfloat16 is a first-class dtype (ref: paddle/fluid/framework/data_type.h
enumerates fp16/fp32/fp64/int*/bool; we add bf16 as the primary mixed-precision type
since the MXU natively consumes bf16).
"""
import jax.numpy as jnp
import numpy as np

# Canonical dtype objects are numpy dtypes (what jax uses under the hood).
float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
uint8 = jnp.dtype(jnp.uint8)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)

_STR2DTYPE = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8,
    "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {int8, int16, int32, int64, uint8}


def convert_dtype(dtype):
    """Normalise a dtype spec (str / np.dtype / jnp type) to a numpy dtype.

    TPU-first: with x64 disabled (the default — 32-bit indices keep gathers and
    iotas on the fast path), int64/float64 requests map to their 32-bit
    counterparts instead of warning on every op.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            d = _STR2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
    else:
        d = jnp.dtype(dtype)
    import jax
    if not jax.config.jax_enable_x64:
        d = {float64: float32, int64: int32, complex128: complex64}.get(d, d)
    return d


def dtype_name(dtype):
    d = jnp.dtype(dtype)
    if d == bfloat16:
        return "bfloat16"
    return d.name


def is_floating_point(dtype):
    return jnp.dtype(dtype) in _FLOATING


def is_integer(dtype):
    return jnp.dtype(dtype) in _INTEGER


def default_dtype():
    from . import state
    return state.get_default_dtype()
