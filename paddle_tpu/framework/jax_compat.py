"""Forward-compat shims for older jax builds.

The repo targets the current jax surface; the container pins jax 0.4.37,
which predates a few public aliases the codebase (and its kernels) use.
Each shim installs the modern name only when missing, mapping it onto the
0.4.x equivalent — on a current jax this module is a no-op, so nothing
here can mask a real API change.
"""
import jax


def install():
    if not hasattr(jax, "typeof"):
        # jax.typeof(x) -> the abstract value (aval) of x. 0.4.x spells
        # it jax.core.get_aval; extras like .vma simply don't exist on
        # the old avals, which callers already probe with getattr.
        jax.typeof = jax.core.get_aval


install()
