"""paddle.save/load analog (ref python/paddle/framework/io.py:202,292 —
pickled nested containers of tensors; tensors serialised as numpy).

Large checkpoints for distributed/sharded state go through orbax in
incubate/checkpoint; this is the single-host object-file path.
"""
import os
import pickle

import numpy as np

from .tensor import Tensor, Parameter


class _TensorPayload:
    """Pickle-stable wrapper recording dtype/shape + raw bytes."""

    def __init__(self, arr: np.ndarray):
        # bfloat16 has no numpy dtype string; store via uint16 view
        self.is_bf16 = arr.dtype.name == "bfloat16"
        if self.is_bf16:
            self.dtype = "bfloat16"
            self.data = arr.view(np.uint16)
        else:
            self.dtype = arr.dtype.str
            self.data = arr
        self.shape = arr.shape

    def restore(self):
        if self.is_bf16:
            import ml_dtypes
            return self.data.view(ml_dtypes.bfloat16)
        return self.data


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy())
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.restore()
        return arr if return_numpy else Tensor(arr)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
