def save(obj, path, **k):
    raise NotImplementedError
def load(path, **k):
    raise NotImplementedError
