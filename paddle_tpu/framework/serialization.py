"""paddle.save/load analog (ref python/paddle/framework/io.py:202,292 —
pickled nested containers of tensors; tensors serialised as numpy).

Writes are ATOMIC: the payload streams into a temp file in the
destination directory, is fsync'd, and lands via `os.replace` — a
crash (or an injected `chaos.CHECKPOINT_WRITE` fault) mid-write leaves
the previous file intact and at most a stray `.tmp.<pid>` behind,
never a truncated checkpoint. The `latest.json` manifest marks the
newest COMPLETE checkpoint prefix in a directory (written only after
every file of the checkpoint landed) and records each file's sha256
(computed while the pickle streams out, no second pass), so
`hapi.Model.load_latest` resumes from a consistent params+optimizer
pair even when the crash hit between the two files.

The digests close the REUSED-PREFIX hole: saving to the same prefix
twice and crashing after the new `.pdparams` landed but before the
`.pdopt` replace would leave the old manifest pointing at new params
+ old optimizer state. The old pair's bytes are gone (overwritten in
place), so such a checkpoint cannot be repaired — but
`latest_checkpoint(verify=True)` (the `load_latest` default) detects
the mismatch and refuses to load the torn pair. Use unique per-step
prefixes (e.g. `ckpt/step{n}`) when a resumable FALLBACK is required.

Large checkpoints for distributed/sharded state go through orbax in
incubate/checkpoint; this is the single-host object-file path.
"""
import hashlib
import json
import os
import pickle
import time

import numpy as np

from ..utils import chaos
from .tensor import Tensor, Parameter

MANIFEST_NAME = "latest.json"
#: manifest schema version: 1 = path/step/files+sha256 (PR 8),
#: 2 = + full train-state file (`.pdtrain`: RNG chains, data cursor,
#: scaler, global step — utils/resume.py) listed and digested like any
#: other checkpoint file, 3 = the `.pdtrain` payload additionally
#: carries the sharded-training provenance record (mesh shape,
#: dp_axis, zero_stage, per-leaf PartitionSpecs —
#: `ShardedTrainStep.sharding_state`), which is what elastic reshard
#: (`fit(resume=True)` onto a different replica count) journals
#: against. Readers accept older manifests (missing version == 1); the
#: version field exists so FUTURE incompatible layouts can be refused
#: instead of half-loaded.
MANIFEST_VERSION = 3


class _TensorPayload:
    """Pickle-stable wrapper recording dtype/shape + raw bytes."""

    def __init__(self, arr: np.ndarray):
        # bfloat16 has no numpy dtype string; store via uint16 view
        self.is_bf16 = arr.dtype.name == "bfloat16"
        if self.is_bf16:
            self.dtype = "bfloat16"
            self.data = arr.view(np.uint16)
        else:
            self.dtype = arr.dtype.str
            self.data = arr
        self.shape = arr.shape

    def restore(self):
        if self.is_bf16:
            import ml_dtypes
            return self.data.view(ml_dtypes.bfloat16)
        return self.data


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy())
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.restore()
        return arr if return_numpy else Tensor(arr)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


class _CheckpointSink:
    """File wrapper that accumulates the payload's sha256 while the
    pickle streams through (recorded in the manifest so `load_latest`
    can detect a checkpoint torn ACROSS files — see module docstring),
    and hosts the checkpoint-write fault point after the first chunk
    lands — a genuine torn write with real bytes on disk, without
    materializing the whole payload just to split it."""

    def __init__(self, f, path):
        self._f = f
        self._path = path
        self._writes = 0
        self._sha = hashlib.sha256()

    def write(self, data):
        n = self._f.write(data)
        self._sha.update(data)
        self._writes += 1
        if self._writes == 1 and chaos.enabled():
            chaos.fire(chaos.CHECKPOINT_WRITE, path=self._path)
        return n

    def hexdigest(self):
        return self._sha.hexdigest()


def _tmp_path(path):
    return f"{path}.tmp.{os.getpid()}"


def _makedirs_for(path):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)


def _atomic_write(target, write_fn):
    """The one crash-atomic write path (checkpoints AND the manifest):
    `write_fn(f)` streams the payload into a temp file in the target's
    directory, then flush + fsync + `os.replace` — the target is either
    its old bytes or the new ones, never a prefix, and a failure leaves
    no `.tmp` litter. Returns write_fn's result."""
    tmp = _tmp_path(target)
    try:
        with open(tmp, "wb") as f:
            out = write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return out


def save(obj, path, protocol=4, **configs):
    """Atomic paddle.save: the pickle STREAMS into a temp file (no
    second in-memory copy of the checkpoint), then fsync + os.replace —
    the destination is either the old bytes or the new bytes, never a
    prefix of the new ones. Returns the payload's sha256 hexdigest
    (for the checkpoint manifest)."""
    path = os.fspath(path)
    _makedirs_for(path)

    def _write(f):
        sink = _CheckpointSink(f, path)
        pickle.dump(_pack(obj), sink, protocol=protocol)
        return sink.hexdigest()

    return _atomic_write(path, _write)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)


# ---------------------------------------------------------------------------
# latest-checkpoint manifest
# ---------------------------------------------------------------------------

def write_manifest(path, step=None, files=None):
    """Atomically mark checkpoint prefix `path` as the newest COMPLETE
    checkpoint of its directory (call only after every file of the
    checkpoint landed). `files` maps basename -> sha256 hexdigest as
    returned by `save` (a bare iterable of names is accepted, recorded
    without digests — those files get an existence check only at
    verify time). Returns the manifest dict written."""
    path = os.fspath(path)
    if files is None:
        files = {}
    elif not isinstance(files, dict):
        files = {name: None for name in files}
    doc = {"version": MANIFEST_VERSION,
           "path": os.path.basename(path),
           "step": None if step is None else int(step),
           "time_unix": round(time.time(), 3),
           "files": {name: files[name] for name in sorted(files)}}
    d = os.path.dirname(os.path.abspath(path))
    target = os.path.join(d, MANIFEST_NAME)
    _atomic_write(target, lambda f: f.write(
        (json.dumps(doc, indent=1) + "\n").encode()))
    return doc


def read_manifest(directory):
    """The directory's manifest dict, or None (missing/unparseable —
    an unparseable manifest means no complete checkpoint is KNOWN,
    which is the safe answer after a torn legacy write)."""
    try:
        with open(os.path.join(directory, MANIFEST_NAME)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and doc.get("path") else None


def _file_sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def verify_checkpoint(directory, doc):
    """True when every file the manifest lists is present and (where a
    digest was recorded) byte-identical to what the manifest's save
    wrote — i.e. the params/optimizer pair on disk really is the pair
    the manifest promised. False on any missing/mismatched file: the
    classic cause is a crash while RE-saving to the same prefix (new
    `.pdparams` already replaced in place, manifest + `.pdopt` still
    the old save's)."""
    files = doc.get("files") or {}
    if not isinstance(files, dict):          # legacy list-form manifest
        files = {name: None for name in files}
    for name, digest in files.items():
        p = os.path.join(directory, name)
        try:
            if digest is None:
                if not os.path.exists(p):
                    return False
            elif _file_sha256(p) != digest:
                return False
        except OSError:
            return False
    return True


def latest_checkpoint(directory, verify=True):
    """Prefix (joined onto `directory`) of the newest complete
    checkpoint, or None when the directory has no manifest — or when
    `verify` (the default) finds the files on disk torn relative to
    the manifest's recorded digests (see `verify_checkpoint`)."""
    doc = read_manifest(directory)
    if doc is None:
        return None
    if verify and not verify_checkpoint(directory, doc):
        return None
    return os.path.join(directory, doc["path"])
