"""Multiprocess DataLoader workers with shared-memory batch transport
(ref python/paddle/fluid/dataloader/dataloader_iter.py:469
_DataLoaderIterMultiProcess + paddle/fluid/memory/allocation/mmap_allocator.h).

Design: forked workers fetch+collate index batches and write each numpy
array of the batch into one POSIX shared-memory segment
(multiprocessing.shared_memory — the mmap_allocator analog); only the
segment name + array headers cross the result queue. The parent maps,
copies out (into jnp on first device use), and unlinks. A watchdog in the
parent's receive loop replaces the reference's SIGCHLD handler: worker
death is detected by exitcode polling and tears the loader down with the
worker's identity instead of hanging on the queue. Batches are re-ordered
by sequence id so shuffle order matches the single-process loader.
"""
import atexit
import itertools
import os
import queue as pyqueue
import multiprocessing as mp
from multiprocessing import shared_memory

import numpy as np

_FORK = mp.get_context("fork")

# shm segments the parent has mapped but not yet unlinked (crash cleanup)
_LIVE_SEGMENTS = set()


@atexit.register
def _cleanup_segments():
    for name in list(_LIVE_SEGMENTS):
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a worker: (id, num_workers, dataset); None in the parent
    (ref dataloader/worker.py get_worker_info)."""
    return _worker_info


def _pack(seq, batch):
    """Collated batch (list/tuple/dict of np arrays) -> shm segment + meta."""
    if isinstance(batch, dict):
        keys = list(batch.keys())
        arrays = [np.ascontiguousarray(np.asarray(batch[k])) for k in keys]
    else:
        keys = None
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in
                  (batch if isinstance(batch, (list, tuple)) else [batch])]
    total = sum(a.nbytes for a in arrays) or 1
    shm = shared_memory.SharedMemory(create=True, size=total)
    metas = []
    off = 0
    for a in arrays:
        shm.buf[off:off + a.nbytes] = a.tobytes()
        metas.append((str(a.dtype), a.shape, off))
        off += a.nbytes
    name = shm.name
    shm.close()
    return {"seq": seq, "shm": name, "metas": metas, "keys": keys}


def _unpack(msg):
    shm = shared_memory.SharedMemory(name=msg["shm"])
    _LIVE_SEGMENTS.add(msg["shm"])
    out = []
    for dtype, shape, off in msg["metas"]:
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        arr = np.frombuffer(shm.buf[off:off + n],
                            dtype=dtype).reshape(shape).copy()
        out.append(arr)
    shm.close()
    shm.unlink()
    _LIVE_SEGMENTS.discard(msg["shm"])
    if msg["keys"] is not None:
        return dict(zip(msg["keys"], out))
    return out


def _worker_loop(worker_id, num_workers, dataset, collate_fn, index_queue,
                 out_queue, iterable_mode, batch_size, drop_last,
                 worker_init_fn):
    global _worker_info
    _worker_info = _WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    try:
        if iterable_mode:
            # each worker owns a strided shard of the stream
            it = iter(dataset)
            seq = worker_id
            stream = itertools.islice(it, worker_id, None, num_workers)
            while True:
                batch = list(itertools.islice(stream, batch_size))
                if not batch or (len(batch) < batch_size and drop_last):
                    break
                out_queue.put(_pack(seq, collate_fn(batch)))
                seq += num_workers
            out_queue.put({"done": worker_id})
            return
        while True:
            item = index_queue.get()
            if item is None:
                out_queue.put({"done": worker_id})
                return
            seq, idxs = item
            out_queue.put(_pack(seq, collate_fn([dataset[i] for i in idxs])))
    except KeyboardInterrupt:
        pass
    except BaseException as e:  # surface the traceback in the parent
        import traceback
        out_queue.put({"error": f"{type(e).__name__}: {e}",
                       "tb": traceback.format_exc(), "worker": worker_id})


class MultiprocessLoaderIter:
    """One epoch of forked-worker loading. Iterate to exhaustion or close().
    """

    def __init__(self, loader):
        self.loader = loader
        n = loader.num_workers
        self.n = n
        self._iterable = loader._iterable_mode
        self._window = max(2, n * loader.prefetch_factor)
        # bounded out queue = the backpressure that stops workers from
        # materialising the whole epoch into /dev/shm
        self._out = _FORK.Queue(maxsize=self._window)
        self._index_queues = []
        self._workers = []
        self._timeout = float(loader.timeout) if loader.timeout else None
        for w in range(n):
            iq = _FORK.Queue() if not self._iterable else None
            p = _FORK.Process(
                target=_worker_loop,
                args=(w, n, loader.dataset, loader.collate_fn, iq, self._out,
                      self._iterable, loader.batch_size
                      if self._iterable else 0,
                      loader.drop_last if self._iterable else False,
                      loader.worker_init_fn),
                daemon=True)
            p.start()
            self._workers.append(p)
            self._index_queues.append(iq)



    def _check_workers(self, done=()):
        for w, p in enumerate(self._workers):
            if p.exitcode is not None and w not in done:
                # exit 0 without the 'done' sentinel (sys.exit in a
                # transform, swallowed KeyboardInterrupt) is just as dead
                self.close()
                raise RuntimeError(
                    f"DataLoader worker {w} (pid {p.pid}) exited "
                    f"(code {p.exitcode}) before finishing its batches — "
                    f"the SIGCHLD watchdog analog "
                    f"(ref dataloader_iter.py _on_child_exit)")

    def __iter__(self):
        import time as _time
        try:
            done = set()
            buffered = {}
            next_seq = 0
            expect = None
            dispatched = 0
            index_iter = None
            closed_queues = False
            if not self._iterable:
                index_iter = enumerate(iter(self.loader.batch_sampler))
                expect = len(self.loader.batch_sampler)
                if expect == 0:
                    return
            received = 0
            last_progress = _time.monotonic()
            while True:
                # incremental dispatch: keep at most `window` index batches
                # outstanding (dispatched - received); the rest wait here
                if index_iter is not None and not closed_queues:
                    while dispatched - received < self._window:
                        try:
                            seq, idxs = next(index_iter)
                        except StopIteration:
                            for iq in self._index_queues:
                                iq.put(None)
                            closed_queues = True
                            break
                        self._index_queues[seq % self.n].put(
                            (seq, list(idxs)))
                        dispatched += 1
                if len(done) == self.n and (
                        expect is None or received >= expect):
                    break
                try:
                    msg = self._out.get(timeout=1.0)
                except pyqueue.Empty:
                    self._check_workers(done)
                    if self._timeout and                             _time.monotonic() - last_progress > self._timeout:
                        self.close()
                        raise RuntimeError(
                            f"DataLoader timed out: no batch for "
                            f"{self._timeout:.0f}s (workers alive but "
                            f"stuck?)")
                    continue
                last_progress = _time.monotonic()
                if "error" in msg:
                    self.close()
                    raise RuntimeError(
                        f"DataLoader worker {msg['worker']} raised:\n"
                        f"{msg['tb']}")
                if "done" in msg:
                    done.add(msg["done"])
                    continue
                received += 1
                if self._iterable:
                    # stream shards end at different times; arrival order
                    # (like the reference's mp iterable loader)
                    yield _unpack(msg)
                    continue
                buffered[msg["seq"]] = msg
                while next_seq in buffered:
                    yield _unpack(buffered.pop(next_seq))
                    next_seq += 1
        finally:
            self.close()

    def close(self):
        # drain undelivered batches so their shm segments are unlinked (an
        # early-exiting consumer must not leak /dev/shm)
        try:
            while True:
                msg = self._out.get_nowait()
                if "shm" in msg:
                    try:
                        seg = shared_memory.SharedMemory(name=msg["shm"])
                        seg.close()
                        seg.unlink()
                    except FileNotFoundError:
                        pass
        except (pyqueue.Empty, OSError, ValueError):
            pass
        for iq in self._index_queues:
            if iq is not None:
                try:
                    iq.cancel_join_thread()
                    iq.close()
                except (OSError, ValueError):
                    pass
        for p in self._workers:
            if p.is_alive():
                p.terminate()
        for p in self._workers:
            p.join(timeout=5)
        try:
            self._out.cancel_join_thread()
            self._out.close()
        except (OSError, ValueError):
            pass
