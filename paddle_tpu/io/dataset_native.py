"""Dataset-driven ingest over the native C++ feed
(ref python/paddle/fluid/dataset.py: DatasetFactory/InMemoryDataset/
QueueDataset over framework/data_feed.h MultiSlotDataFeed + data_set.h
DatasetImpl).

The C++ side (native/src/data_feed.cc) parses multi-slot text, holds records
in memory, shuffles with a seed, and assembles batches on a background thread
behind a bounded channel. Python pops whole batches as numpy (ragged slots as
(values, lod) pairs — the LoDTensor analog in dense XLA-friendly form).
"""
import ctypes

import numpy as np

from ..utils.native_build import load_native


class _Slot:
    def __init__(self, name, dtype="int64", dense_dim=0):
        assert dtype in ("float32", "int64"), dtype
        self.name = name
        self.is_float = dtype == "float32"
        self.dense_dim = int(dense_dim)


class DatasetBase:
    """Multi-slot dataset over the native feed."""

    def __init__(self):
        self._lib = load_native()
        self._h = self._lib.pt_feed_create()
        self._slots = []
        self._batch_size = 1
        self._drop_last = False
        self._filelist = []
        self._started = False

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pt_feed_destroy(self._h)
                self._h = None
        # interpreter teardown: ctypes globals may already be None'd, so
        # ANY exception type here is shutdown noise, not a real failure
        except Exception:   # ptlint: disable=swallowed-exception
            pass

    # ------------------------------------------------------------ config
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_use_var(self, slots):
        """Declare slots, in on-disk order. Each entry: (name, dtype) or
        (name, dtype, dense_dim) with dtype 'float32'|'int64'."""
        assert not self._slots, "slots already set"
        for s in slots:
            slot = _Slot(*s) if isinstance(s, (tuple, list)) else _Slot(s)
            self._slots.append(slot)
            self._lib.pt_feed_add_slot(
                self._h, slot.name.encode(), int(slot.is_float),
                slot.dense_dim)

    def set_filelist(self, files):
        self._filelist = list(files)

    # ------------------------------------------------------------ ingest
    def load_into_memory(self):
        for f in self._filelist:
            n = self._lib.pt_feed_load_file(self._h, str(f).encode())
            if n < 0:
                raise ValueError(
                    self._lib.pt_feed_error(self._h).decode() or
                    f"failed to parse {f}")

    def local_shuffle(self, seed=0):
        self._lib.pt_feed_shuffle(self._h, int(seed))

    def global_shuffle(self, fleet=None, seed=0):
        # single-host: identical to local_shuffle; multi-host exchange is the
        # PS runtime's job (ref data_set.h global shuffle via gloo)
        self.local_shuffle(seed)

    def get_memory_data_size(self):
        return int(self._lib.pt_feed_size(self._h))

    def release_memory(self):
        self._lib.pt_feed_clear(self._h)

    # ------------------------------------------------------------ batches
    def _read_slot(self, i, bs):
        slot = self._slots[i]
        lp = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.pt_feed_slot_lod(self._h, i, ctypes.byref(lp))
        lod = np.ctypeslib.as_array(lp, shape=(n,)).copy()
        if slot.is_float:
            vp = ctypes.POINTER(ctypes.c_float)()
            n = self._lib.pt_feed_slot_fvals(self._h, i, ctypes.byref(vp))
            vals = (np.ctypeslib.as_array(vp, shape=(n,)).copy()
                    if n else np.empty((0,), "f4"))
        else:
            vp = ctypes.POINTER(ctypes.c_int64)()
            n = self._lib.pt_feed_slot_ivals(self._h, i, ctypes.byref(vp))
            vals = (np.ctypeslib.as_array(vp, shape=(n,)).copy()
                    if n else np.empty((0,), "i8"))
        if slot.dense_dim > 0:
            return vals.reshape(bs, slot.dense_dim)
        return vals, lod

    def __iter__(self):
        """Yield dict name -> dense [bs, dim] array, or (values, lod) for
        ragged slots."""
        self._lib.pt_feed_start(self._h, self._batch_size,
                                int(self._drop_last), 8)
        try:
            while True:
                bs = self._lib.pt_feed_next(self._h)
                if bs == 0:
                    break
                yield {s.name: self._read_slot(i, bs)
                       for i, s in enumerate(self._slots)}
        finally:
            self._lib.pt_feed_stop(self._h)


class InMemoryDataset(DatasetBase):
    """ref fluid/dataset.py:329 InMemoryDataset."""


class QueueDataset(DatasetBase):
    """ref fluid/dataset.py QueueDataset — streaming; here load_into_memory
    is implicit at iteration start if not done."""

    def __iter__(self):
        if self.get_memory_data_size() == 0:
            self.load_into_memory()
        return super().__iter__()


class DatasetFactory:
    """ref fluid/dataset.py:23."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")
