"""paddle_tpu.io — Dataset / DataLoader / samplers
(ref python/paddle/io/__init__.py, fluid/dataloader/*).

Single-process iterator with async host->device prefetch (the buffered_reader
double-buffering analog, ref operators/reader/buffered_reader.cc): batches are
assembled in numpy on host and handed over ahead of consumption so H2D overlaps
compute. A thread-pool path covers the multiprocess DataLoader use case (TPU
hosts have many cores; numpy transforms release the GIL).
"""
import collections
import itertools
import queue
import threading

import numpy as np

from ..framework.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                        for t in tensors]
        assert all(t.shape[0] == self.tensors[0].shape[0] for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, ofs = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + n].tolist()))
        ofs += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """ref fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """ref python/paddle/io/DistributedBatchSampler — shards indices per rank."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Stack samples -> numpy batch arrays (ref fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


class DataLoader:
    """ref fluid/reader.py:149 DataLoader. return_list=True semantics (2.0)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, use_shared_memory=False, timeout=0,
                 worker_init_fn=None, prefetch_factor=2):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _raw_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _wrap(self, batch):
        if isinstance(batch, (tuple, list)):
            return [Tensor(b) for b in batch]
        if isinstance(batch, dict):
            return {k: Tensor(v) for k, v in batch.items()}
        return [Tensor(batch)]

    def __iter__(self):
        if self.num_workers <= 0:
            if self.use_buffer_reader:
                yield from self._buffered_iter(self._raw_batches())
            else:
                for batch in self._raw_batches():
                    yield self._wrap(batch)
            return
        if self.use_shared_memory:
            # forked workers + shared-memory transport + watchdog
            # (ref dataloader_iter.py:469 _DataLoaderIterMultiProcess)
            from .multiprocess import MultiprocessLoaderIter
            for batch in MultiprocessLoaderIter(self):
                yield self._wrap(batch)
            return
        yield from self._worker_iter()

    def _buffered_iter(self, gen, depth=2):
        """Double-buffer: materialise `depth` batches ahead on a thread.
        The cancel event lets an abandoned iterator (consumer `break`s) unblock
        and retire the producer instead of leaking it on a full queue."""
        q = queue.Queue(maxsize=depth)
        stop = object()
        err = []
        cancel = threading.Event()

        def producer():
            try:
                for b in gen:
                    item = self._wrap(b)
                    while not cancel.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if cancel.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                if not cancel.is_set():
                    q.put(stop)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is stop:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            cancel.set()

    def _worker_iter(self):
        """Thread-pool prefetch (multiprocess DataLoader analog,
        ref fluid/dataloader/dataloader_iter.py:469)."""
        from concurrent.futures import ThreadPoolExecutor
        if self._iterable_mode:
            yield from self._buffered_iter(self._raw_batches(),
                                           depth=self.prefetch_factor)
            return
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            def fetch(idxs):
                return self.collate_fn([self.dataset[i] for i in idxs])

            pending = collections.deque()
            depth = self.num_workers * self.prefetch_factor
            try:
                for idxs in self.batch_sampler:
                    pending.append(pool.submit(fetch, idxs))
                    if len(pending) >= depth:
                        yield self._wrap(pending.popleft().result())
                while pending:
                    yield self._wrap(pending.popleft().result())
            finally:
                for f in pending:
                    f.cancel()

    def iter_from(self, start_batch):
        """Resume-seek: iterate this epoch starting at batch index
        `start_batch` WITHOUT fetching/collating the skipped batches.
        The batch sampler's index draws for the skipped batches still
        happen (so a shuffled epoch's permutation — and the global
        numpy RNG position — advance exactly as in the original run),
        but `dataset[i]`/collate are never called for them: seeking an
        epoch of expensive reads costs sampler arithmetic only.

        Exact-resume caveat (docs/robustness.md): per-item transforms
        that draw from the GLOBAL numpy RNG are not replayed by the
        seek — `Model.fit`'s default fetch-and-discard fast-forward is
        the bitwise-exact path for such datasets; this method is the
        cheap path for RNG-free readers. Iterable datasets and worker
        pools fall back to fetch-and-discard (their readers have no
        index to seek)."""
        start = max(0, int(start_batch))
        if start == 0:
            yield from self
            return
        if self._iterable_mode or self.num_workers > 0:
            it = iter(self)
            consumed = 0
            for _ in it:
                consumed += 1
                if consumed >= start:
                    break
            yield from it
            return

        def seeked():
            for i, idxs in enumerate(self.batch_sampler):
                if i < start:
                    continue
                yield self.collate_fn([self.dataset[j] for j in idxs])

        if self.use_buffer_reader:
            yield from self._buffered_iter(seeked())
        else:
            for batch in seeked():
                yield self._wrap(batch)

    @staticmethod
    def from_generator(feed_list=None, capacity=2, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        raise NotImplementedError(
            "legacy DataLoader.from_generator: wrap the generator in an "
            "IterableDataset instead")


def get_worker_info():
    """In a multiprocess DataLoader worker: shard info; else None."""
    from .multiprocess import get_worker_info as _gwi
    return _gwi()


def __getattr__(name):
    # native C++ feed classes load (and build) the shared lib on first use
    if name in ("DatasetFactory", "InMemoryDataset", "QueueDataset"):
        from . import dataset_native
        return getattr(dataset_native, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
