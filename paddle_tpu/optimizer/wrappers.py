"""Optimizer wrappers: EMA, ModelAverage, Lookahead, GradientMerge.

TPU-native equivalents of the reference's python optimizer wrappers
(ref python/paddle/fluid/optimizer.py — ExponentialMovingAverage:3466,
ModelAverage:3157, LookaheadOptimizer:5230, GradientMergeOptimizer:5402):
the reference rewrites the static program to add accumulator vars + ops;
here each wrapper keeps its accumulators as jnp arrays and exposes the same
apply()/restore()/minimize surface. All accumulator math is one fused XLA
dispatch per step (jnp expressions over the whole param list via tree_map).
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


class ExponentialMovingAverage:
    """EMA of parameter values (ref fluid/optimizer.py:3466): call update()
    each step after optimizer.step(); apply()/restore() swap EMA weights in
    and out for evaluation. Includes the reference's bias correction
    (1 - decay^t)."""

    def __init__(self, decay=0.999, thres_steps=None, parameters=None,
                 name=None):
        if parameters is None:
            raise ValueError("pass parameters=model.parameters()")
        self._decay = decay
        self._params = [p for p in parameters if p.trainable]
        # EMA_0 = 0 (matching ref fluid/optimizer.py ExponentialMovingAverage)
        # — the /(1 - decay^t) bias correction below is only valid for a
        # zero-initialized accumulator.
        self._ema = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._step = 0
        self._backup = None

    def update(self):
        self._step += 1
        d = self._decay
        for p in self._params:
            key = id(p)
            self._ema[key] = d * self._ema[key] + (1.0 - d) * p._data

    def _unbiased(self, key, live):
        if self._step == 0:
            return live  # no update yet: zeros accumulator is meaningless
        corr = 1.0 - self._decay ** self._step
        return self._ema[key] / corr

    def apply(self, need_restore=True):
        """Swap EMA weights into the params; returns a context manager so
        `with ema.apply(): evaluate()` restores automatically."""
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._unbiased(id(p), p._data).astype(p._data.dtype)
        ema = self

        @contextlib.contextmanager
        def ctx():
            try:
                yield
            finally:
                if need_restore:
                    ema.restore()
        return ctx()

    def restore(self):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    def state_dict(self):
        return {f"ema_{i}": Tensor(self._ema[id(p)])
                for i, p in enumerate(self._params)} | \
               {"step": self._step}

    def set_state_dict(self, sd):
        self._step = int(sd.get("step", 0))
        for i, p in enumerate(self._params):
            v = sd.get(f"ema_{i}")
            if v is not None:
                self._ema[id(p)] = v._data if isinstance(v, Tensor) \
                    else jnp.asarray(v)


class ModelAverage:
    """Running average of parameters over a sliding window
    (ref fluid/optimizer.py:3157: accumulated sums with
    min_average_window/max_average_window). update() each step;
    apply()/restore() for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("pass parameters=model.parameters()")
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._params = [p for p in parameters if p.trainable]
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._count = 0
        self._backup = None

    def update(self):
        self._count += 1
        window = max(self._min_w, min(
            self._max_w, int(self._count * self._rate) or 1))
        decay = max(0.0, 1.0 - 1.0 / window)
        for p in self._params:
            key = id(p)
            self._sum[key] = self._sum[key] * decay + p._data

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        # effective count of the geometric window
        window = max(self._min_w, min(
            self._max_w, int(self._count * self._rate) or 1))
        decay = max(0.0, 1.0 - 1.0 / window)
        n_eff = (1.0 - decay ** max(self._count, 1)) / (1.0 - decay) \
            if decay < 1.0 else max(self._count, 1)
        for p in self._params:
            p._data = (self._sum[id(p)] / n_eff).astype(p._data.dtype)
        ma = self

        @contextlib.contextmanager
        def ctx():
            try:
                yield
            finally:
                if need_restore:
                    ma.restore()
        return ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None


class LookaheadOptimizer:
    """Lookahead (ref fluid/optimizer.py:5230): fast optimizer steps k
    times, then slow weights interpolate: slow += alpha * (fast - slow),
    fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._params = inner_optimizer._parameters
        self._slow = {id(p): jnp.array(p._data) for p in self._params}
        self._steps = 0

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            a = self.alpha
            for p in self._params:
                key = id(p)
                slow = self._slow[key] + a * (p._data - self._slow[key])
                self._slow[key] = slow
                # distinct buffer: the inner optimizer donates p._data on
                # its next step, which must not delete our slow copy
                p._data = jnp.copy(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()


class GradientMergeOptimizer:
    """k-step gradient accumulation before one real update
    (ref fluid/optimizer.py:5402 and meta_optimizers/GradientMergeOptimizer):
    on TPU this also serves as the micro-batch accumulation primitive when
    a batch doesn't fit HBM."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg
        self._params = inner_optimizer._parameters
        self._acc = None
        self._steps = 0

    def step(self):
        if self._acc is None:
            self._acc = {id(p): jnp.zeros_like(p._data)
                         for p in self._params}
        from ..framework.selected_rows import SelectedRows
        for p in self._params:
            if p.grad is not None:
                g = p.grad.to_dense() if isinstance(p.grad, SelectedRows) \
                    else p.grad._data
                self._acc[id(p)] = self._acc[id(p)] + g
        self._steps += 1
        if self._steps % self.k_steps == 0:
            scale = 1.0 / self.k_steps if self.avg else 1.0
            for p in self._params:
                g = self._acc[id(p)] * scale
                p.grad = Tensor(g)
            self.inner_optimizer.step()
            self._acc = None
        # grads consumed either way
        for p in self._params:
            p.grad = None

    def clear_grad(self):
        for p in self._params:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()

    def get_lr(self):
        return self.inner_optimizer.get_lr()
