"""LR schedulers (ref python/paddle/optimizer/lr.py — LRScheduler family)."""
import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = self.base_lr
        self.verbose = verbose
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        # None included: ReduceOnPlateau's `best=None` must round-trip
        # (a resume that silently kept a stale `best` would change the
        # plateau decisions, and with them the LR trajectory)
        return {k: v for k, v in self.__dict__.items()
                if v is None or isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, sd):
        self.__dict__.update(sd)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5
                * min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr)
                * (1 - step / decay_steps) ** self.power + self.end_lr)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) \
            else None
        self.target_lr = (learning_rate if not self.lr_sched else None)
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / self.warmup_steps) + self.start_lr
        if self.lr_sched is not None:
            self.lr_sched.step(self.last_epoch - self.warmup_steps)
            return self.lr_sched.last_lr
        return self.target_lr

    def state_dict(self):
        sd = super().state_dict()
        if self.lr_sched is not None:
            # nested under its own key (the wrapped LRScheduler object
            # is not base-serializable); restored explicitly below so
            # the base __dict__.update can never replace the scheduler
            # object with a plain dict
            sd["_wrapped_sched"] = self.lr_sched.state_dict()
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        nested = sd.pop("_wrapped_sched", None)
        super().set_state_dict(sd)
        if nested is not None and self.lr_sched is not None:
            self.lr_sched.set_state_dict(nested)

    set_dict = set_state_dict


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min)
                * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad_epochs = 0
        self.cooldown_counter = 0
        super().__init__(learning_rate, -1, verbose)

    def get_lr(self):
        return self.last_lr if hasattr(self, "last_lr") else self.base_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            self.last_lr = getattr(self, "last_lr", self.base_lr)
            return
        cur = float(metrics.item() if hasattr(metrics, "item") else metrics)
        if self.best is None:
            self.best = cur
        else:
            better = (cur < self.best - abs(self.best) * self.threshold
                      if self.mode == "min"
                      else cur > self.best + abs(self.best) * self.threshold) \
                if self.threshold_mode == "rel" else \
                (cur < self.best - self.threshold if self.mode == "min"
                 else cur > self.best + self.threshold)
            if better:
                self.best = cur
                self.num_bad_epochs = 0
            else:
                self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up = int(self.phase_pct * self.total_steps)
        if step <= up and up > 0:
            pct = step / up
            return self.initial_lr + (self.max_lr - self.initial_lr) * (
                1 - math.cos(math.pi * pct)) / 2
        pct = (step - up) / max(self.total_steps - up, 1)
        return self.end_lr + (self.max_lr - self.end_lr) * (
            1 + math.cos(math.pi * pct)) / 2


class MultiplicativeDecay(LRScheduler):
    """ref lr.py MultiplicativeDecay: lr_{t} = lr_{t-1} * lam(t)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        lr = self.base_lr
        for e in range(1, self.last_epoch + 1):
            lr = lr * self.lr_lambda(e)
        return lr


class CyclicLR(LRScheduler):
    """ref lr.py CyclicLR (triangular policies over a base/max band)."""

    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = float(max_learning_rate)
        self.up = int(step_size_up)
        self.down = int(step_size_down
                        if step_size_down is not None else step_size_up)
        if self.up <= 0 or self.down <= 0:
            raise ValueError("CyclicLR step sizes must be positive")
        self.mode = mode
        self.exp_gamma = exp_gamma
        if scale_fn is not None:
            self.scale_fn, self.scale_mode = scale_fn, scale_mode
        elif mode == "triangular":
            self.scale_fn, self.scale_mode = (lambda x: 1.0), "cycle"
        elif mode == "triangular2":
            self.scale_fn = lambda x: 1.0 / (2.0 ** (x - 1))
            self.scale_mode = "cycle"
        elif mode == "exp_range":
            self.scale_fn = lambda x: exp_gamma ** x
            self.scale_mode = "iterations"
        else:
            raise ValueError(f"unknown CyclicLR mode {mode!r}")
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.up + self.down
        it = max(self.last_epoch, 0)
        cycle = it // total + 1
        pos = it % total
        frac = pos / self.up if pos < self.up \
            else 1.0 - (pos - self.up) / self.down
        span = (self.max_lr - self.base_lr) * frac
        x = cycle if self.scale_mode == "cycle" else it
        return self.base_lr + span * self.scale_fn(x)


class CosineAnnealingWarmRestarts(LRScheduler):
    """ref lr.py CosineAnnealingWarmRestarts (SGDR): cosine anneal over
    T_i, restart, T_{i+1} = T_i * T_mult."""

    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        if T_0 <= 0 or T_mult < 1:
            raise ValueError("T_0 must be > 0 and T_mult >= 1")
        self.T_0 = int(T_0)
        self.T_mult = int(T_mult)
        self.eta_min = float(eta_min)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = max(self.last_epoch, 0)
        t_i = self.T_0
        if self.T_mult == 1:
            e = e % self.T_0            # O(1); the loop would be O(e/T_0)
        else:
            while e >= t_i:
                e -= t_i
                t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) \
            * (1 + math.cos(math.pi * e / t_i)) / 2
