"""Optimizer base + update rules (ref python/paddle/optimizer/optimizer.py and the
device kernels in paddle/fluid/operators/optimizers/: sgd_op, momentum_op,
adam_op, adamw, lamb_op, lars_momentum_op, rmsprop, adagrad, adadelta).

Design: each optimizer defines a pure `_update(p, g, lr, *state) -> (new_p,
*new_state)` rule. Eagerly, `step()` runs it through one fused XLA executable per
(shape,dtype) bucket; functionally, `apply_gradients` maps it over a pytree inside
a jit'd train step (the ParallelExecutor-analog hot path) with buffer donation so
weights update in place on HBM.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, Parameter
from .lr import LRScheduler


class Optimizer:
    _state_names = ()          # per-param state slot names, e.g. ("moment",)

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (float, int)) and weight_decay:
            from ..regularizer import L2Decay
            self._weight_decay = L2Decay(float(weight_decay))
        else:
            self._weight_decay = weight_decay
        self._accumulators = {}    # id(param) -> dict(state_name -> jnp array)
        self._global_step = 0
        self._multi_precision = False   # subclasses expose the kwarg
        self.helper = None

    # ------------------------------------------------------------------ lr
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # ------------------------------------------------------------------ state
    def _ensure_state(self, p):
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self._init_state(p._data)
        return self._accumulators[key]

    def _mp_param(self, arr):
        """multi_precision applies to low-precision params: the optimizer
        keeps an fp32 MASTER copy, updates it, and casts down — without
        it, bf16 weights round away updates smaller than ~0.8%% of the
        weight magnitude (ref multi_precision on Adam/Momentum/SGD:
        master weights in the fp16/bf16 kernels)."""
        return (self._multi_precision
                and arr.dtype in (jnp.bfloat16, jnp.float16))

    def _init_state(self, arr):
        if self._mp_param(arr):
            # fp32 slots alongside the fp32 master: _update computes in
            # f32, and param-dtype slots would flip the state pytree's
            # dtypes after step 1 (a full recompile under jit)
            st = {name: jnp.zeros(arr.shape, jnp.float32)
                  for name in self._state_names}
            st["master"] = arr.astype(jnp.float32)
            return st
        return {name: jnp.zeros_like(arr) for name in self._state_names}

    def _hyper(self):
        """Scalar hyperparams passed to _update (beyond lr)."""
        return ()

    @staticmethod
    def _update(p, g, lr, hyper, state):
        raise NotImplementedError

    # ------------------------------------------------------------------ step
    def step(self):
        from ..framework.selected_rows import SelectedRows
        self._global_step += 1
        params_grads = [(p, p.grad) for p in self._parameters
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            # clipping needs dense magnitudes: densify row-sparse grads
            params_grads = [
                (p, Tensor(g.to_dense()) if isinstance(g, SelectedRows)
                 else g) for p, g in params_grads]
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        hyper = self._hyper()
        update = _jitted_update(type(self))
        for p, g in params_grads:
            if isinstance(g, SelectedRows):
                if self._can_row_update():
                    self._sparse_step(p, g, lr, hyper)
                    continue
                # stateful non-lazy optimizers need the dense semantics
                # (moments decay on untouched rows too — ref adam_op
                # non-lazy SelectedRows branch densifies likewise)
                g = Tensor(g.to_dense())
            state = self._ensure_state(p)
            master = state.get("master")
            base = master if master is not None else p._data
            # decay/regularizer against the same values _update sees —
            # for multi_precision that is the fp32 master (a bf16 g + wd*p
            # would round the decay term away entirely)
            g_arr = g._data.astype(base.dtype)
            if self._weight_decay is not None and \
                    getattr(p, "regularizer", None) is None:
                g_arr = self._weight_decay._append(base, g_arr)
            elif getattr(p, "regularizer", None) is not None:
                g_arr = p.regularizer._append(base, g_arr)
            plr = lr * getattr(p, "learning_rate", 1.0)
            new_p, new_state = update(
                base, g_arr, jnp.asarray(plr, jnp.float32), hyper,
                tuple(state[n] for n in self._state_names),
                jnp.asarray(self._global_step, jnp.int32))
            if master is not None:
                state["master"] = new_p
                p._data = new_p.astype(p._data.dtype)
            else:
                p._data = new_p
            for n, s in zip(self._state_names, new_state):
                state[n] = s

    def _can_row_update(self):
        """Row-wise sparse update is exact for stateless rules (SGD) and is
        the documented lazy_mode semantics for stateful ones. Disabled
        under multi_precision: the scatter would update p._data behind
        the fp32 master's back, and the next dense step would revert it
        — those grads densify instead (correct, just not lazy)."""
        if self._multi_precision:
            return False
        return not self._state_names or getattr(self, "_lazy_mode", False)

    def _sparse_step(self, p, g, lr, hyper):
        """Update only the touched rows (ref sgd_op.h SparseSGDFunctor /
        adam lazy_mode): gather rows of param+state, apply the dense rule
        on the slice, scatter back."""
        merged = g.merge()
        rows = merged.rows
        vals = merged.values.astype(p._data.dtype)
        state_d = self._ensure_state(p)
        plr = lr * getattr(p, "learning_rate", 1.0)
        p_rows = p._data[rows]
        # decay/regularizer on the touched rows (matching the dense path;
        # lazy semantics regularize rows when they are updated)
        if getattr(p, "regularizer", None) is not None:
            vals = p.regularizer._append(p_rows, vals)
        elif self._weight_decay is not None:
            vals = self._weight_decay._append(p_rows, vals)
        st_rows = tuple(state_d[n][rows] for n in self._state_names)
        new_rows, new_st = type(self)._update(
            p_rows, vals, jnp.asarray(plr, jnp.float32), hyper, st_rows,
            jnp.asarray(self._global_step, jnp.int32))
        p._data = p._data.at[rows].set(new_rows)
        for n, s in zip(self._state_names, new_st):
            state_d[n] = state_d[n].at[rows].set(s)

    minimize_called = False

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """ref optimizer.py minimize. Static mode (under program_guard):
        appends backward + update OpDescs to the program
        (static/backward.py minimize_static); dygraph: backward + step."""
        from ..framework import state as _state
        rec = _state.get_static_recorder()
        if rec is not None and rec.name_of(loss) is not None:
            from ..static.backward import minimize_static
            return minimize_static(self, loss, program=rec.program,
                                   parameters=parameters,
                                   no_grad_set=no_grad_set)
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    # ------------------------------------------------------- functional path
    def init_opt_state(self, params, parameters=None):
        """params: dict name -> jnp array. Returns opt state pytree.
        Delegates to _init_state so subclass slot dtypes (Adam's f32
        moments) and multi_precision master weights apply identically in
        the eager and jitted paths.

        `parameters` (name -> live Parameter, e.g.
        dict(model.named_parameters())) is the RESUME path: slots
        already accumulated on this optimizer — a checkpoint restored
        via set_state_dict, or prior eager/synced steps — seed the
        functional state instead of zeros. Without it a rebuilt
        TrainStep would silently reset Adam moments (and with them the
        loss trajectory) on every resume. Copies are handed out: the
        compiled step donates its state buffers."""
        out = {}
        for name, arr in params.items():
            st = None
            if parameters is not None:
                p = parameters.get(name)
                if p is not None:
                    st = self._accumulators.get(id(p))
            out[name] = ({k: jnp.copy(v) for k, v in st.items()} if st
                         else self._init_state(arr))
        return out

    def apply_gradients_fn(self):
        """Returns a pure fn(params, grads, opt_state, lr, step) ->
        (new_params, new_opt_state) usable under jit/pjit."""
        hyper = self._hyper()
        update = type(self)._update
        clip = self._grad_clip
        wd = self._weight_decay
        state_names = self._state_names

        def apply_fn(params, grads, opt_state, lr, step):
            names = list(params.keys())
            gs = [grads[n] for n in names]
            if clip is not None:
                gs = clip.apply_arrays(gs)
            new_params, new_state = {}, {}
            for n, g in zip(names, gs):
                p = params[n]
                if g is None:
                    new_params[n] = p
                    new_state[n] = opt_state[n]
                    continue
                # multi_precision: update the fp32 master, cast down
                master = opt_state[n].get("master")
                base = master if master is not None else p
                g = g.astype(base.dtype)
                if wd is not None:
                    g = wd._append(base, g)
                st = tuple(opt_state[n][sn] for sn in state_names)
                np_, nst = update(base, g, lr, hyper, st, step)
                new_state[n] = dict(zip(state_names, nst))
                if master is not None:
                    new_state[n]["master"] = np_
                    np_ = np_.astype(p.dtype)
                new_params[n] = np_
            return new_params, new_state

        return apply_fn

    # ------------------------------------------------------------- save/load
    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._parameters):
            key = p.name or f"param_{i}"
            st = self._accumulators.get(id(p))
            if st:
                for n, arr in st.items():
                    # copy: step() donates the state buffers (see
                    # UPDATE_DONATE_ARGNUMS), so a live reference would
                    # be invalidated by the next step on donation-
                    # honoring backends — checkpoint-then-continue must
                    # keep working (same contract as TrainStep.sync)
                    sd[f"{key}.{n}"] = Tensor(jnp.copy(arr))
        sd["global_step"] = self._global_step
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, sd):
        self._global_step = int(sd.get("global_step", 0))
        if "LR_Scheduler" in sd and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(sd["LR_Scheduler"])
        for i, p in enumerate(self._parameters):
            key = p.name or f"param_{i}"
            st = self._ensure_state(p)
            for n in (*self._state_names, "master"):
                k = f"{key}.{n}"
                if k in sd:
                    v = sd[k]
                    # copy, never alias: jnp.asarray is a no-op on a jax
                    # array, and step() donates these slots (see
                    # UPDATE_DONATE_ARGNUMS) — an aliased checkpoint
                    # buffer would be deleted out from under the caller
                    # on the next step
                    st[n] = jnp.copy(v.numpy() if isinstance(v, Tensor)
                                     else v)
            if "master" in st and f"{key}.master" not in sd:
                # resuming from a checkpoint without a master slot: seed
                # it from the just-loaded weights, else the next step
                # would revert them to the stale pre-load master
                st["master"] = p._data.astype(jnp.float32)

    set_dict = set_state_dict


# param AND state are donated: step() discards both after every call
# (state[n] is rebound to the returned tuple), so XLA may update the
# moments in place instead of transiently holding 2x the optimizer
# state per parameter — jxaudit's donation-missing rule gates this
# (scripts/jxaudit.py, program `optimizer_update`), and its registry
# reads THIS constant so the audited declaration cannot drift
UPDATE_DONATE_ARGNUMS = (0, 4)


@functools.lru_cache(maxsize=None)
def _jitted_update(cls):
    """One compiled+donated executable per optimizer class; XLA caches per
    shape/dtype (the OpKernel cache analog)."""
    return jax.jit(cls._update, donate_argnums=UPDATE_DONATE_ARGNUMS,
                   static_argnums=())


# --------------------------------------------------------------------- rules


class SGD(Optimizer):
    _state_names = ()

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        return p - lr.astype(p.dtype) * g, ()


class Momentum(Optimizer):
    _state_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)
        self._multi_precision = bool(multi_precision)

    def _hyper(self):
        return (self._momentum, 1.0 if self._use_nesterov else 0.0)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        mu, nesterov = hyper
        (v,) = state
        v_new = mu * v + g
        delta = jnp.where(nesterov > 0.5, g + mu * v_new, v_new)
        return p - lr.astype(p.dtype) * delta, (v_new,)


class Adam(Optimizer):
    _state_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = bool(lazy_mode)
        self._multi_precision = bool(multi_precision)

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        b1, b2, eps = hyper
        m, v = state
        g32 = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        return p - upd.astype(p.dtype), (m, v)

    def _init_state(self, arr):
        # fp32 moments even for bf16 params (always; the "master" slot
        # for the WEIGHTS is opt-in via multi_precision)
        st = {n: jnp.zeros(arr.shape, jnp.float32)
              for n in self._state_names}
        if self._mp_param(arr):
            st["master"] = arr.astype(jnp.float32)
        return st


class AdamW(Adam):
    """Decoupled weight decay (ref optimizers/adamw — decay applied to param
    directly, not through grads)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode=lazy_mode,
                         multi_precision=multi_precision)
        self._coeff = float(weight_decay) if isinstance(weight_decay,
                                                        (int, float)) else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon, self._coeff)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        b1, b2, eps, coeff = hyper
        m, v = state
        g32 = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = lr * (mhat / (jnp.sqrt(vhat) + eps) + coeff * p.astype(jnp.float32))
        return p - upd.astype(p.dtype), (m, v)


class Adamax(Optimizer):
    _state_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        b1, b2, eps = hyper
        m, u = state
        m = b1 * m + (1 - b1) * g
        u = jnp.maximum(b2 * u, jnp.abs(g))
        t = step.astype(jnp.float32)
        lr_t = lr / (1 - b1 ** t)
        return (p - (lr_t * m / (u + eps)).astype(p.dtype)), (m, u)


class Adagrad(Optimizer):
    _state_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_value = initial_accumulator_value

    def _hyper(self):
        return (self._epsilon,)

    def _init_state(self, arr):
        return {"moment": jnp.full(arr.shape, self._init_value, jnp.float32)}

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        (eps,) = hyper
        (mom,) = state
        mom = mom + jnp.square(g.astype(jnp.float32))
        upd = lr * g.astype(jnp.float32) / (jnp.sqrt(mom) + eps)
        return p - upd.astype(p.dtype), (mom,)


class Adadelta(Optimizer):
    _state_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _hyper(self):
        return (self._epsilon, self._rho)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        eps, rho = hyper
        sq_g, sq_u = state
        g32 = g.astype(jnp.float32)
        sq_g = rho * sq_g + (1 - rho) * jnp.square(g32)
        upd = jnp.sqrt(sq_u + eps) / jnp.sqrt(sq_g + eps) * g32
        sq_u = rho * sq_u + (1 - rho) * jnp.square(upd)
        return p - (lr * upd).astype(p.dtype), (sq_g, sq_u)


class RMSProp(Optimizer):
    _state_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _hyper(self):
        return (self._rho, self._epsilon, self._momentum,
                1.0 if self._centered else 0.0)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        rho, eps, mom, centered = hyper
        ms, mg, macc = state
        g32 = g.astype(jnp.float32)
        ms = rho * ms + (1 - rho) * jnp.square(g32)
        mg = jnp.where(centered > 0.5, rho * mg + (1 - rho) * g32, mg)
        denom = jnp.where(centered > 0.5, ms - jnp.square(mg), ms)
        macc = mom * macc + lr * g32 / jnp.sqrt(denom + eps)
        return p - macc.astype(p.dtype), (ms, mg, macc)


class Lamb(Optimizer):
    """ref optimizers/lamb_op.cc — layerwise-adaptive Adam for large batch."""
    _state_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon, self._lamb_wd)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        b1, b2, eps, wd = hyper
        m, v = state
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - (lr * trust * r).astype(p.dtype), (m, v)


class Lars(Momentum):
    """LARS momentum (ref optimizers/lars_momentum_op.cc)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _hyper(self):
        return (self._momentum, self._lars_coeff, self._lars_wd)

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        mu, coeff, wd = hyper
        (v,) = state
        p32, g32 = p.astype(jnp.float32), g.astype(jnp.float32)
        w_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            coeff * w_norm / (g_norm + wd * w_norm + 1e-12), 1.0)
        v_new = mu * v + lr * local_lr * (g32 + wd * p32)
        return p - v_new.astype(p.dtype), (v_new,)


class Ftrl(Optimizer):
    """FTRL-proximal (ref operators/optimizers/ftrl_op.h): per-coordinate
    adaptive lr with L1/L2 regularization in the update itself — the
    sparse-model optimizer the reference pairs with PS training."""

    _state_names = ("squared", "linear")

    def __init__(self, learning_rate=0.05, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._l1 = float(l1)
        self._l2 = float(l2)
        self._lr_power = float(lr_power)

    def _hyper(self):
        return (self._l1, self._l2, self._lr_power)

    def _init_state(self, arr):
        return {"squared": jnp.zeros(arr.shape, jnp.float32),
                "linear": jnp.zeros(arr.shape, jnp.float32)}

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        l1, l2, lr_power = hyper
        sq, lin = state
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        new_sq = sq + gf * gf
        sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / lr
        lin = lin + gf - sigma * pf
        quad = new_sq ** (-lr_power) / lr + 2.0 * l2
        pre = jnp.clip(lin, -l1, l1) - lin
        new_p = jnp.where(jnp.abs(lin) > l1, pre / quad, 0.0)
        return new_p.astype(p.dtype), (new_sq, lin)


class Dpsgd(Optimizer):
    """Differentially-private SGD (ref operators/optimizers/dpsgd_op.cc):
    per-update gradient clipping to `clip` + gaussian noise scaled by
    batch_size/sigma.

    RNG discipline: a FRESH key is drawn eagerly in _hyper() every step
    (so paddle.seed governs the noise and the key enters the compiled
    update as a traced argument, never a baked constant), and each
    parameter carries a unique `noise_idx` in its state so same-shaped
    parameters get decorrelated noise. Under a whole-step compiler
    (TrainStep) the key is captured once at build time; noise still
    varies per step/param via fold_in(step, idx)."""

    _state_names = ("noise_idx",)

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._clip = float(clip)
        self._batch_size = float(batch_size)
        self._sigma = float(sigma)
        self._noise_counter = 0

    def _hyper(self):
        from ..framework import state as _st
        return (self._clip, self._batch_size, self._sigma,
                _st.next_rng_key())

    def _init_state(self, arr):
        self._noise_counter += 1
        return {"noise_idx": jnp.asarray(self._noise_counter, jnp.uint32)}

    @staticmethod
    def _update(p, g, lr, hyper, state, step):
        clip, batch_size, sigma, key = hyper
        (idx,) = state
        gf = g.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(gf * gf))
        gf = gf * jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
        key = jax.random.fold_in(jax.random.fold_in(key, step), idx)
        noise = jax.random.normal(key, gf.shape) * (clip * sigma
                                                    / batch_size)
        return (p - lr * (gf + noise).astype(p.dtype)).astype(p.dtype), \
            (idx,)
