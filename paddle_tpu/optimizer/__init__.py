"""paddle_tpu.optimizer (ref python/paddle/optimizer/__init__.py)."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb, Lars, Ftrl,
                        Dpsgd)
from .wrappers import (ExponentialMovingAverage, ModelAverage,
                       LookaheadOptimizer, GradientMergeOptimizer)
