"""paddle_tpu.serving.paged — block-table KV-cache subsystem.

vLLM-style paged attention for the serving engine: a fixed pool of KV
blocks per layer (`BlockPool`: free-list allocator with refcounts,
hash-based prefix sharing with copy-on-write, lazy eviction), per-slot
block tables traced into the SAME two compiled programs the dense
engine discipline established, and chunked prefill so long-prompt
admission folds between decode waves instead of stalling them. See
docs/serving.md ("Paged KV cache").

    from paddle_tpu.serving import PagedServingEngine, Scheduler
    engine = PagedServingEngine(model, num_slots=8, max_len=512,
                                block_size=16, num_blocks=129)
    sched = Scheduler(engine)          # same scheduler, same Requests
"""
from .block_pool import BlockPool, BlockPoolExhausted
from .engine import (HandoffRefused, PagedServingEngine,
                     SpeculativePagedEngine)

__all__ = ["BlockPool", "BlockPoolExhausted", "HandoffRefused",
           "PagedServingEngine", "SpeculativePagedEngine"]
