"""BlockPool: host-side memory manager for the paged KV cache.

The device side is a fixed pool of KV blocks per layer —
`[num_blocks, kv_heads, block_size, head_dim]` x2, allocated once at
engine construction (serving pays HBM for the blocks it CONFIGURES, not
`num_slots * max_len`). This class owns the block ids: a free list with
refcounts, per-request allocation, and a hash-based prefix cache so
identical prompt prefixes (the shared-system-prompt pattern that
dominates at millions-of-users scale) map to the SAME physical blocks.

Invariants the engine relies on:

  * block 0 is the scratch block — never allocated, never hashed; the
    compiled programs redirect inactive/invalid lanes' writes there;
  * only FULL, immutable prompt blocks are hashed (chain hash: a
    block's identity covers its entire token prefix, which for a causal
    LM determines its K/V content exactly), and a hash is registered
    only AFTER the prefill chunk that wrote the block ran — a
    concurrent admission can never share a block whose content is not
    on the device yet;
  * a freed block (refcount 0) keeps its hash and stays reusable from
    the free list — the prefix cache survives request churn and is
    evicted lazily, oldest-freed first, only when allocation needs the
    block back;
  * `cow()` is the copy-on-write guard: writing through a block with
    refcount > 1 must first move the writer onto a private copy. With
    full-block-only sharing the decode frontier always lands in a
    private block, so this fires only as a safety net — but it is the
    load-bearing guarantee that sharing can never corrupt a neighbour.

Speculative decoding (serving/paged SpeculativePagedEngine) layers a
DRAFT model's KV pools onto the SAME block ids: one table row names the
same token span in the target pools and the draft pools, so allocation,
refcounts, prefix sharing and copy-on-write govern both at once — there
is no second allocator to leak from. Blocks allocated ahead for drafted
tokens that verification REJECTS are released the same wave
(`_rollback_spec_blocks`); `outstanding()` below is the audit surface
the chaos harness uses to prove no speculative block outlives its
tokens.

Thread-model: driven single-threaded from the scheduler's wave loop
(`Scheduler._wave_lock` serializes every engine call); producer threads
touch only the queue, never the pool.
"""
import collections
import hashlib

from ...utils import chaos
from .. import metrics as serving_metrics


class BlockPoolExhausted(RuntimeError):
    """Allocation failed: every usable block is referenced. The
    scheduler treats this as CAPACITY, not as a request fault — the
    request is queued behind the blocks it is waiting for (or preempted
    to free some), never crashed."""


class BlockPool:
    SCRATCH = 0

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (one scratch + "
                             f"one usable), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # free list in eviction order (oldest-freed first); block 0 is
        # the scratch block and never enters it
        self._free = collections.OrderedDict(
            (b, None) for b in range(1, self.num_blocks))
        self._ref = [0] * self.num_blocks
        self._hash_to_block = {}
        self._block_hash = {}
        self.prefix_hits = 0
        self.prefix_misses = 0
        self._publish()

    # ------------------------------------------------------------- state
    @property
    def usable(self):
        """Allocatable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def used(self):
        """Blocks currently referenced by at least one request."""
        return self.usable - len(self._free)

    def refcount(self, block):
        return self._ref[block]

    def outstanding(self):
        """{block_id: refcount} for every live (refcount > 0) block —
        the refcount-audit surface: after a stream drains this must be
        empty, and during one, every entry must be owned by some slot's
        table (the speculative rollback audit names leaked blocks with
        this instead of just counting them)."""
        return {b: r for b, r in enumerate(self._ref) if r > 0}

    def _publish(self):
        serving_metrics.record_block_usage(self.used, self.usable)

    # -------------------------------------------------------- allocation
    def alloc(self, n):
        """Take `n` fresh blocks (refcount 1 each). Prefers blocks with
        no cached hash; evicts prefix-cache entries oldest-freed first
        only when it must. Raises BlockPoolExhausted when fewer than `n`
        blocks are free — atomically: either all `n` or none."""
        n = int(n)
        if chaos.enabled():
            # payload (truthy) = simulated exhaustion; raise-action =
            # simulated allocator crash (must surface as a fault, not
            # be absorbed as capacity)
            if chaos.value(chaos.CACHE_ALLOC, need=n,
                           free=len(self._free)):
                raise BlockPoolExhausted(
                    f"injected exhaustion: need {n} block(s)")
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} block(s), {len(self._free)} free of "
                f"{self.usable} usable")
        out = []
        for _ in range(n):
            blk = next((b for b in self._free
                        if b not in self._block_hash), None)
            if blk is None:
                blk = next(iter(self._free))       # evict oldest cached
            del self._free[blk]
            h = self._block_hash.pop(blk, None)
            if h is not None and self._hash_to_block.get(h) == blk:
                del self._hash_to_block[h]
            self._ref[blk] = 1
            out.append(blk)
        self._publish()
        return out

    def acquire(self, block):
        """Add one reference to an already-referenced block (sharing)."""
        if self._ref[block] < 1:
            raise ValueError(f"block {block} is not live")
        self._ref[block] += 1

    def release(self, blocks):
        """Drop one reference per block; refcount 0 returns the block to
        the free list (keeping its prefix-cache hash, if any — the
        cached content stays matchable until evicted by alloc)."""
        for blk in blocks:
            if self._ref[blk] < 1:
                raise ValueError(f"double free of block {blk}")
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                self._free[blk] = None
        self._publish()

    def cow(self, block):
        """Copy-on-write guard: `block` unchanged when exclusively owned;
        otherwise allocate a fresh block, move one reference off the
        shared one, and return the new id — the CALLER must copy the
        device content before writing through it."""
        if self._ref[block] <= 1:
            return block
        new, = self.alloc(1)
        self._ref[block] -= 1
        return new

    # ------------------------------------------------------ prefix cache
    @staticmethod
    def chain_hash(prev, tokens):
        """Digest of one full block's tokens chained onto its prefix —
        equal chain hashes mean equal (prefix, block) token content,
        which for a causal LM means equal K/V content at equal
        positions. A chained sha256, NOT the builtin hash(): lookups
        serve K/V content across requests on digest equality alone, so
        a collision (adversarially constructible for hash(), which is
        also salted per process) would leak one request's cache into
        another's decode."""
        h = hashlib.sha256(b"" if prev is None else prev)
        h.update(repr(tuple(int(t) for t in tokens)).encode())
        return h.digest()

    def match_prefix(self, tokens):
        """Longest run of cached full blocks covering `tokens`' prefix.
        Returns (blocks, hashes): per matched block, one NEW reference
        (caller must release on failure) and its chain hash. Does NOT
        count hits/misses — the caller counts via count_prefix only on
        a SUCCESSFUL admission, so a request retrying at the queue head
        under pool pressure doesn't inflate the dedup-efficacy rate."""
        bs = self.block_size
        nfull = len(tokens) // bs
        blocks, hashes, h = [], [], None
        for i in range(nfull):
            h = self.chain_hash(h, tokens[i * bs:(i + 1) * bs])
            blk = self._hash_to_block.get(h)
            if blk is None:
                break
            if self._ref[blk] == 0:            # revive off the free list
                del self._free[blk]
            self._ref[blk] += 1
            blocks.append(blk)
            hashes.append(h)
        self._publish()
        return blocks, hashes

    def peek_prefix_hashes(self, hashes):
        """READ-ONLY affinity probe over a precomputed chain-hash walk
        (`prompt_hashes`): how many LEADING hashes this pool holds
        right now. Takes no references, counts no hits, publishes
        nothing — the fleet router scores every replica per admission
        with this, and a probe that mutated refcounts or the hit rate
        would corrupt both (`match_prefix` is the acquiring variant)."""
        n = 0
        for h in hashes:
            if h not in self._hash_to_block:
                break
            n += 1
        return n

    def count_prefix(self, hits, misses):
        """Count one admitted prompt's prefix-cache outcome (hits =
        full blocks served from cache, misses = full blocks prefill
        must compute)."""
        self.prefix_hits += int(hits)
        self.prefix_misses += int(misses)
        serving_metrics.record_prefix_lookup(int(hits), int(misses))

    def prompt_hashes(self, tokens):
        """Chain hashes for every full block of `tokens` (registration
        schedule for the prefill path)."""
        bs = self.block_size
        out, h = [], None
        for i in range(len(tokens) // bs):
            h = self.chain_hash(h, tokens[i * bs:(i + 1) * bs])
            out.append(h)
        return out

    # --------------------------------------------------- block-level handoff
    def export_blocks(self, blocks):
        """Manifest for a block-level handoff: one entry per live block,
        carrying its prefix-cache chain hash (None for unhashed blocks —
        the partially-filled tail, or hashes another block won). The
        DEVICE content rides separately (the engine's export_slot_kv);
        this is the allocator-side half of the transfer: the importing
        pool re-allocates from the manifest and re-registers the hashes
        only after the content lands, preserving the never-share-an-
        unwritten-block invariant across pools."""
        for blk in blocks:
            if blk == self.SCRATCH:
                raise ValueError("scratch block cannot be exported")
            if self._ref[blk] < 1:
                raise ValueError(f"block {blk} is not live")
        return [{"hash": self._block_hash.get(blk)} for blk in blocks]

    def import_blocks(self, manifest):
        """Allocate fresh local blocks to receive an exported manifest —
        atomically (all or none; BlockPoolExhausted is CAPACITY, handled
        upstream exactly like an admission under pool pressure). Returns
        the new block ids in manifest order. Hashes are NOT registered
        here: the caller registers them via register_hash only after the
        device content is actually written into the new blocks."""
        return self.alloc(len(manifest))

    def register_hash(self, block, chain_hash):
        """Enter a WRITTEN full prompt block into the prefix cache. A
        hash already mapping to another live block keeps the existing
        mapping (first writer wins; the duplicate content is simply not
        shared)."""
        if self._ref[block] < 1:
            raise ValueError(f"block {block} is not live")
        if chain_hash in self._hash_to_block:
            return
        self._hash_to_block[chain_hash] = block
        self._block_hash[block] = chain_hash

    def stats(self):
        return {
            "used": self.used, "usable": self.usable,
            "block_size": self.block_size,
            "cached_hashes": len(self._hash_to_block),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
        }
