"""PagedServingEngine: block-table KV cache over the ServingEngine
wave machinery.

The dense engine pays `num_slots * max_len` HBM per layer whatever the
traffic actually holds; BENCH_serving.json put real occupancy at
0.26–0.45 — most of that stream is padding. Here the cache is a fixed
POOL of `[num_blocks, kv_heads, block_size, head_dim]` KV blocks per
layer and slots reference block TABLES (host-managed int32 id rows,
`serving.paged.BlockPool`): HBM scales with the blocks you configure,
utilisation scales with actual tokens, and identical prompt prefixes
dedupe onto shared blocks.

Still exactly TWO compiled programs, fully static shapes (the
compile-once discipline — table entries are VALUES, not shapes):

  * decode wave — the dense wave plus one traced `[S, nblk]` block
    table: each lane's K/V scatters through its table row and attention
    reads the gathered per-row view (`nn/transformer.py
    gather_block_kv` / `scatter_block_kv_at`).
  * prefill chunk — ONE fixed-size chunk of one slot's prompt at a
    traced absolute offset. Long prompts run chunk-by-chunk BETWEEN
    decode waves (the scheduler advances one chunk per round), so
    admission never stalls decoding; prompts shorter than a chunk
    complete in one step, and chunks fully covered by prefix-cache hits
    are skipped outright.

Block bookkeeping is host-authoritative like the rest of the slot
state: the table upload is `S * nblk` int32 per wave. Allocation happens
between waves; a wave whose lane cannot get a block (pool exhausted) is
excluded from that wave and reported in `last_starved_slots` — the
scheduler preempts it by recompute (requeue with prompt + generated
tokens; the freed blocks' prefix hashes make the re-prefill mostly
cache hits).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ...utils import chaos, telemetry
from ..engine import (ServingEngine, _raw, _select_first_token,
                      _select_wave_tokens)
from .block_pool import BlockPool, BlockPoolExhausted


class PagedServingEngine(ServingEngine):
    """Block-table batched decode executor.

    model: a causal LM exposing init_paged_cache / decode_step(...,
        block_tables=) / prefill_chunk (GPTForPretraining,
        LlamaForCausalLM).
    max_len: per-request horizon; must be a multiple of block_size
        (table width = max_len // block_size).
    num_blocks: pool size INCLUDING the scratch block (block 0).
        Default num_slots * max_len // block_size + 1 — dense-equivalent
        capacity; size it smaller to oversubscribe (utilisation follows
        actual tokens, starved lanes preempt gracefully).
    prefill_chunk_len: prompt chunk size (default min(64, max_len)).
    prefix_sharing: hash full prompt blocks and dedupe identical
        prefixes (copy-on-write guarded; see BlockPool).
    """

    def __init__(self, model, num_slots=4, max_len=256, block_size=16,
                 num_blocks=None, prefill_chunk_len=None, cache_dtype=None,
                 jit_compile=True, seed=0, prefix_sharing=True):
        if max_len % block_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"block_size {block_size}")
        self.block_size = int(block_size)
        self.blocks_per_slot = int(max_len) // self.block_size
        if num_blocks is None:
            num_blocks = int(num_slots) * self.blocks_per_slot + 1
        self.prefill_chunk_len = int(prefill_chunk_len
                                     or min(64, int(max_len)))
        if self.prefill_chunk_len > max_len:
            raise ValueError(
                f"prefill_chunk_len {self.prefill_chunk_len} > max_len "
                f"{max_len}")
        self.prefix_sharing = bool(prefix_sharing)
        self.block_pool = BlockPool(num_blocks, self.block_size)
        self._copy_fn = None
        super().__init__(model, num_slots=num_slots, max_len=max_len,
                         prefill_len=self.prefill_chunk_len,
                         cache_dtype=cache_dtype, jit_compile=jit_compile,
                         seed=seed)
        self._slot_blocks = [[] for _ in range(self.num_slots)]
        self._tables = np.zeros((self.num_slots, self.blocks_per_slot),
                                np.int32)

    def _make_caches(self):
        return self.model.init_paged_cache(self.block_pool.num_blocks,
                                           self.block_size, self.max_len,
                                           dtype=self.cache_dtype)

    # ---------------------------------------------------------- programs
    def _build_programs(self):
        model = self.model

        def decode_wave(p, b, caches, tables, tok, pos, active, sample,
                        temps, poison, key):
            out, _ = model.functional_call(p, b, tok[:, None], caches,
                                           pos, method="decode_step",
                                           block_tables=tables)
            logits, new_caches = out
            lo = _raw(logits)[:, 0, :].astype(jnp.float32)
            nxt, new_pos, finite = _select_wave_tokens(
                lo, tok, pos, active, sample, temps, poison, key)
            return nxt, new_pos, finite, new_caches

        def prefill_chunk(p, b, caches, table, chunk, chunk_start,
                          valid_len, frontier, sample, temp, key):
            out, _ = model.functional_call(
                p, b, chunk[None, :], caches, method="prefill_chunk",
                block_tables=table[None, :], chunk_start=chunk_start,
                valid_len=valid_len, frontier=frontier)
            logits, new_caches = out
            # frontier logits [1, 1, V]: only the FINAL chunk's value is
            # consumed on host; earlier chunks compute a [V] row that is
            # simply ignored (static shapes beat a conditional head)
            lo = _raw(logits)[0, 0].astype(jnp.float32)
            first = _select_first_token(lo, sample, temp, key)
            return first, new_caches

        self._decode_wave_fn = decode_wave
        self._prefill_fn = prefill_chunk
        self._program_donate_argnums = (2,)

        if self._jit:
            # the block pools are donated exactly like the dense cache:
            # the engine always replaces its cache reference with the
            # program output, so XLA updates the pool in place
            self._decode_wave = telemetry.instrument_jit(
                jax.jit(decode_wave,
                        donate_argnums=self._program_donate_argnums),
                "paged_decode_wave")
            self._prefill = telemetry.instrument_jit(
                jax.jit(prefill_chunk,
                        donate_argnums=self._program_donate_argnums),
                "paged_prefill_chunk")
        else:
            self._decode_wave = decode_wave
            self._prefill = prefill_chunk

    # --------------------------------------------------------- admission
    def validate_prompt(self, prompt):
        """Chunked prefill removes the dense bucket limit: any prompt
        that fits the horizon (with one position to decode into) and the
        pool's total capacity is admissible."""
        n = len(prompt)
        if n + 1 > self.max_len:
            return (f"prompt length {n} leaves no room to decode under "
                    f"max_len {self.max_len}")
        need = (n + 1 + self.block_size - 1) // self.block_size
        if need > self.block_pool.usable:
            return (f"prompt needs {need} KV blocks, pool has only "
                    f"{self.block_pool.usable} usable")
        return None

    def begin_prefill(self, slot, prompt, do_sample=False,
                      temperature=1.0):
        """Admit a prompt: match shared prefix blocks, allocate the rest
        (BlockPoolExhausted = capacity, handled by the scheduler as
        queueing pressure, never a request fault), and stage the chunk
        schedule. Chunks fully covered by prefix-cache hits are
        skipped — a fully-cached prompt still runs its LAST chunk, which
        produces the frontier logits (the K/V are cached; the first
        TOKEN never is)."""
        why = self.validate_prompt(prompt)
        if why:
            raise ValueError(why)
        if self.slot_active[slot] or slot in self._pending_prefill:
            raise RuntimeError(f"slot {slot} is busy")
        prompt = [int(t) for t in prompt]
        n, bs = len(prompt), self.block_size
        need = (n + 1 + bs - 1) // bs
        shared, hashes = ([], [])
        if self.prefix_sharing:
            shared, hashes = self.block_pool.match_prefix(prompt)
        try:
            fresh = self.block_pool.alloc(need - len(shared))
        except BaseException:
            # exhaustion AND crash paths (e.g. an injected allocator
            # raise): the matched prefix references must go back, or a
            # failed admission permanently shrinks pool capacity
            self.block_pool.release(shared)
            raise
        if self.prefix_sharing:
            # counted only now, on successful admission — exhaustion
            # retries at the queue head must not inflate the rate
            self.block_pool.count_prefix(len(shared),
                                         n // bs - len(shared))
        blocks = shared + fresh
        self._slot_blocks[slot] = blocks
        self._tables[slot, :] = 0
        self._tables[slot, :len(blocks)] = blocks
        chunk = self.prefill_chunk_len
        start = (len(shared) * bs // chunk) * chunk
        start = min(start, ((n - 1) // chunk) * chunk)
        self._pending_prefill[slot] = {
            "prompt": prompt, "n": n, "next": start,
            "sample": bool(do_sample), "temp": float(temperature),
            "hashes": (self.block_pool.prompt_hashes(prompt)
                       if self.prefix_sharing else []),
            "next_hash": len(shared),
        }

    def prefill_step(self, slot):
        """Run ONE chunk of the slot's staged prompt. Returns the
        request's first generated token when the final chunk ran, None
        while chunks remain (decode waves continue in between)."""
        st = self._pending_prefill[slot]
        if chaos.enabled():
            # host-side, before the donated pool reaches the program — a
            # fired fault leaves device state untouched; the scheduler
            # fails just this request and frees its blocks
            chaos.fire(chaos.PREFILL, slot=slot, chunk_start=st["next"])
        c0, C, n, bs = st["next"], self.prefill_chunk_len, st["n"], \
            self.block_size
        trace = self._slot_trace.get(slot)
        if trace is not None:
            # chunk-indexed progress marker inside the request's PREFILL
            # span: a long chunked admission's folding between decode
            # waves is visible per chunk in the exported trace
            telemetry.trace_instant(
                trace[0], f"PREFILL_CHUNK[{c0 // C}]", pid=trace[1],
                slot=slot, chunk_start=c0, prompt_len=n)
        valid = min(C, n - c0)
        chunk = np.zeros((C,), np.int32)
        chunk[:valid] = st["prompt"][c0:c0 + valid]
        last = c0 + C >= n
        frontier = (n - 1) - c0 if last else 0
        self._key, sub = jax.random.split(self._key)
        first, self._caches = self._prefill(
            self._params, self._buffers, self._caches,
            jnp.asarray(self._tables[slot]), jnp.asarray(chunk),
            jnp.int32(c0), jnp.int32(valid), jnp.int32(frontier),
            jnp.asarray(st["sample"]), jnp.float32(st["temp"]), sub)
        # full prompt blocks written by this chunk enter the prefix
        # cache — only now, so a concurrent admission can never share a
        # block whose content is not on the device yet
        if self.prefix_sharing:
            end = c0 + valid
            while (st["next_hash"] < len(st["hashes"])
                   and (st["next_hash"] + 1) * bs <= end):
                i = st["next_hash"]
                self.block_pool.register_hash(self._slot_blocks[slot][i],
                                              st["hashes"][i])
                st["next_hash"] += 1
        st["next"] = c0 + C
        if not last:
            return None
        del self._pending_prefill[slot]
        first = int(np.asarray(first))
        self.slot_active[slot] = True
        self.slot_pos[slot] = n
        self.slot_tok[slot] = first
        self.slot_sample[slot] = st["sample"]
        self.slot_temp[slot] = st["temp"]
        return first

    def prefill_slot(self, slot, prompt, do_sample=False, temperature=1.0):
        """Synchronous admission (runs every chunk back-to-back) — the
        dense-engine surface, kept for direct engine users; the
        scheduler uses begin_prefill/prefill_step to fold chunks between
        waves."""
        self.begin_prefill(slot, prompt, do_sample=do_sample,
                           temperature=temperature)
        while True:
            first = self.prefill_step(slot)
            if first is not None:
                return first

    # ------------------------------------------------------------- waves
    def _prepare_wave(self, active_now):
        """Back each active lane's next write position with a block.
        Allocation failure excludes the lane from this wave (its table
        row still maps unallocated entries to scratch, so the frozen
        lane's in-program write is harmless) and reports it for
        preemption. A shared write target (safety net — full-block
        sharing keeps the frontier private by construction) is
        copy-on-write'd first."""
        starved = []
        for s, live in enumerate(active_now):
            if not live:
                continue
            bi = self.slot_pos[s] // self.block_size
            blocks = self._slot_blocks[s]
            try:
                if bi >= len(blocks):
                    blk, = self.block_pool.alloc(1)
                    blocks.append(blk)
                    self._tables[s, bi] = blk
                elif self.block_pool.refcount(blocks[bi]) > 1:
                    self._ensure_private(s, bi)
            except BlockPoolExhausted:
                starved.append(s)
                active_now[s] = False
        self.last_starved_slots = starved
        return active_now

    def _wave_args(self, active_now, poison, key):
        # the program scatters EVERY lane's K/V unconditionally (fixed
        # shapes); a lane not in THIS wave (free, mid-prefill, starved)
        # would write its stale token through its table row into a live
        # block — a mid-chunked-prefill slot's table is already
        # populated, possibly with SHARED blocks. Upload scratch rows
        # for those lanes so the write lands in block 0 by design.
        tables = np.where(np.asarray(active_now, bool)[:, None],
                          self._tables, np.int32(BlockPool.SCRATCH))
        return (self._params, self._buffers, self._caches,
                jnp.asarray(tables),
                jnp.asarray(self.slot_tok, jnp.int32),
                jnp.asarray(self.slot_pos, jnp.int32),
                jnp.asarray(active_now, bool),
                jnp.asarray(self.slot_sample, bool),
                jnp.asarray(self.slot_temp, jnp.float32),
                jnp.asarray(poison), key)

    # ----------------------------------------------------- copy-on-write
    def _ensure_private(self, slot, bi):
        """Give the slot a private copy of table entry `bi` (the pool
        moves the reference; the device content is copied by a tiny
        jitted program, compiled lazily — the normal flow never diverges
        into a shared block, so this almost never runs)."""
        blocks = self._slot_blocks[slot]
        blk = blocks[bi]
        new = self.block_pool.cow(blk)
        if new == blk:
            return
        self._caches = self._copy_block(self._caches, blk, new)
        blocks[bi] = new
        self._tables[slot, bi] = new

    def _copy_block(self, caches, src, dst):
        if self._copy_fn is None:
            def copy_fn(caches, src, dst):
                return [(ck.at[dst].set(ck[src]), cv.at[dst].set(cv[src]))
                        for ck, cv in caches]
            self._copy_fn = (telemetry.instrument_jit(
                jax.jit(copy_fn, donate_argnums=(0,)), "paged_cow_copy")
                if self._jit else copy_fn)
        return self._copy_fn(caches, jnp.int32(src), jnp.int32(dst))

    # ------------------------------------------------------------- slots
    def retire_slot(self, slot):
        """Free the slot AND its blocks. Freed blocks keep their prefix
        hashes (lazy eviction), so a follow-up request with the same
        prompt — or this request re-admitted after preemption — re-hits
        the cache instead of recomputing."""
        super().retire_slot(slot)
        blocks = self._slot_blocks[slot]
        if blocks:
            self.block_pool.release(blocks)
        self._slot_blocks[slot] = []
        self._tables[slot, :] = 0

    def _health(self):
        # cache_blocks_used/total mirror the gauges of the same name:
        # the fleet router (and any LB) reads pool pressure from ONE
        # /healthz fetch instead of scraping /metrics
        h = super()._health()
        h.update(block_size=self.block_size,
                 cache_blocks_used=self.block_pool.used,
                 cache_blocks_total=self.block_pool.usable,
                 prefix_cache_hits=self.block_pool.prefix_hits,
                 prefix_cache_misses=self.block_pool.prefix_misses)
        return h
