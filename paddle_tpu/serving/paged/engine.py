"""PagedServingEngine: block-table KV cache over the ServingEngine
wave machinery.

The dense engine pays `num_slots * max_len` HBM per layer whatever the
traffic actually holds; BENCH_serving.json put real occupancy at
0.26–0.45 — most of that stream is padding. Here the cache is a fixed
POOL of `[num_blocks, kv_heads, block_size, head_dim]` KV blocks per
layer and slots reference block TABLES (host-managed int32 id rows,
`serving.paged.BlockPool`): HBM scales with the blocks you configure,
utilisation scales with actual tokens, and identical prompt prefixes
dedupe onto shared blocks.

Still exactly TWO compiled programs, fully static shapes (the
compile-once discipline — table entries are VALUES, not shapes):

  * decode wave — the dense wave plus one traced `[S, nblk]` block
    table: each lane's K/V scatters through its table row and attention
    reads the gathered per-row view (`nn/transformer.py
    gather_block_kv` / `scatter_block_kv_at`).
  * prefill chunk — ONE fixed-size chunk of one slot's prompt at a
    traced absolute offset. Long prompts run chunk-by-chunk BETWEEN
    decode waves (the scheduler advances one chunk per round), so
    admission never stalls decoding; prompts shorter than a chunk
    complete in one step, and chunks fully covered by prefix-cache hits
    are skipped outright.

Block bookkeeping is host-authoritative like the rest of the slot
state: the table upload is `S * nblk` int32 per wave. Allocation happens
between waves; a wave whose lane cannot get a block (pool exhausted) is
excluded from that wave and reported in `last_starved_slots` — the
scheduler preempts it by recompute (requeue with prompt + generated
tokens; the freed blocks' prefix hashes make the re-prefill mostly
cache hits).
"""
import jax
import jax.numpy as jnp
import numpy as np

import hashlib

from ...nn import paged_attention
from ...utils import chaos, telemetry
from .. import blackbox
from ..engine import (ServingEngine, _filter_top_k_top_p, _raw,
                      _select_first_token, _select_wave_tokens)
from .block_pool import BlockPool, BlockPoolExhausted

#: block-level KV handoff payload schema version (export_slot_kv /
#: import_handoff) — bumped when the payload layout changes so a
#: mixed-version fleet refuses the transfer instead of mis-scattering
HANDOFF_VERSION = 1


class HandoffRefused(RuntimeError):
    """A block-level KV handoff payload failed verification (digest
    mismatch, incompatible pool geometry, or a version skew). This is a
    REQUEST fault, never capacity: the importing scheduler fails only
    the handed-off request — decoding over corrupt or misaligned K/V
    would silently produce wrong tokens, which is strictly worse than
    an error (the PR 10/11 digest-verified-state discipline)."""


def _handoff_digest(layers, n_tokens, block_size):
    """sha256 over the payload's device content + the geometry that
    gives it meaning — the serving analog of the checkpoint manifest's
    per-file digests (and of the replica supervisor's weight digest):
    the importing engine verifies bytes, not trust."""
    h = hashlib.sha256()
    h.update(f"v{HANDOFF_VERSION}:{n_tokens}:{block_size}".encode())
    for arr in layers:
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class PagedServingEngine(ServingEngine):
    """Block-table batched decode executor.

    model: a causal LM exposing init_paged_cache / decode_step(...,
        block_tables=) / prefill_chunk (GPTForPretraining,
        LlamaForCausalLM).
    max_len: per-request horizon; must be a multiple of block_size
        (table width = max_len // block_size).
    num_blocks: pool size INCLUDING the scratch block (block 0).
        Default num_slots * max_len // block_size + 1 — dense-equivalent
        capacity; size it smaller to oversubscribe (utilisation follows
        actual tokens, starved lanes preempt gracefully).
    prefill_chunk_len: prompt chunk size (default min(64, max_len)).
    prefix_sharing: hash full prompt blocks and dedupe identical
        prefixes (copy-on-write guarded; see BlockPool).
    paged_kernel: which fused paged-attention implementation the
        engine's programs trace ("reference" | "lax" | "pallas" |
        "auto"; None defers to PT_PAGED_KERNEL / the process default —
        see nn/paged_attention.py). Resolved at construction and pinned
        for every program this engine compiles; reported in /healthz.
    """

    def __init__(self, model, num_slots=4, max_len=256, block_size=16,
                 num_blocks=None, prefill_chunk_len=None, cache_dtype=None,
                 jit_compile=True, seed=0, prefix_sharing=True,
                 paged_kernel=None):
        self.paged_kernel = paged_attention.resolve_kernel(paged_kernel)
        if max_len % block_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"block_size {block_size}")
        self.block_size = int(block_size)
        self.blocks_per_slot = int(max_len) // self.block_size
        if num_blocks is None:
            num_blocks = int(num_slots) * self.blocks_per_slot + 1
        self.prefill_chunk_len = int(prefill_chunk_len
                                     or min(64, int(max_len)))
        if self.prefill_chunk_len > max_len:
            raise ValueError(
                f"prefill_chunk_len {self.prefill_chunk_len} > max_len "
                f"{max_len}")
        self.prefix_sharing = bool(prefix_sharing)
        self.block_pool = BlockPool(num_blocks, self.block_size)
        self._copy_fn = None
        self._handoff_gather_fn = None
        self._handoff_scatter_fn = None
        super().__init__(model, num_slots=num_slots, max_len=max_len,
                         prefill_len=self.prefill_chunk_len,
                         cache_dtype=cache_dtype, jit_compile=jit_compile,
                         seed=seed)
        self._slot_blocks = [[] for _ in range(self.num_slots)]
        self._tables = np.zeros((self.num_slots, self.blocks_per_slot),
                                np.int32)

    def _make_caches(self):
        return self.model.init_paged_cache(self.block_pool.num_blocks,
                                           self.block_size, self.max_len,
                                           dtype=self.cache_dtype)

    # ---------------------------------------------------------- programs
    def _build_programs(self):
        model, kern = self.model, self.paged_kernel

        def decode_wave(p, b, caches, tables, tok, pos, active, sample,
                        temps, top_k, top_p, bias, poison, key):
            # the scope pins this engine's kernel at TRACE time — the
            # compiled wave keeps whatever it resolved, regardless of
            # the process default when later engines trace
            with paged_attention.kernel_scope(kern):
                out, _ = model.functional_call(p, b, tok[:, None], caches,
                                               pos, method="decode_step",
                                               block_tables=tables)
            logits, new_caches = out
            lo = _raw(logits)[:, 0, :].astype(jnp.float32)
            nxt, new_pos, finite = _select_wave_tokens(
                lo, tok, pos, active, sample, temps, top_k, top_p, bias,
                poison, key)
            return nxt, new_pos, finite, new_caches

        def prefill_chunk(p, b, caches, table, chunk, chunk_start,
                          valid_len, frontier, sample, temp, top_k,
                          top_p, bias, key):
            with paged_attention.kernel_scope(kern):
                out, _ = model.functional_call(
                    p, b, chunk[None, :], caches, method="prefill_chunk",
                    block_tables=table[None, :], chunk_start=chunk_start,
                    valid_len=valid_len, frontier=frontier)
            logits, new_caches = out
            # frontier logits [1, 1, V]: only the FINAL chunk's value is
            # consumed on host; earlier chunks compute a [V] row that is
            # simply ignored (static shapes beat a conditional head)
            lo = _raw(logits)[0, 0].astype(jnp.float32)
            first = _select_first_token(lo, sample, temp, top_k, top_p,
                                        bias, key)
            return first, new_caches

        self._decode_wave_fn = decode_wave
        self._prefill_fn = prefill_chunk
        self._program_donate_argnums = (2,)

        if self._jit:
            # the block pools are donated exactly like the dense cache:
            # the engine always replaces its cache reference with the
            # program output, so XLA updates the pool in place
            self._decode_wave = telemetry.instrument_jit(
                jax.jit(decode_wave,
                        donate_argnums=self._program_donate_argnums),
                "paged_decode_wave")
            self._prefill = telemetry.instrument_jit(
                jax.jit(prefill_chunk,
                        donate_argnums=self._program_donate_argnums),
                "paged_prefill_chunk")
        else:
            self._decode_wave = decode_wave
            self._prefill = prefill_chunk

    def describe(self):
        """Replay-relevant construction config (see ServingEngine
        .describe): the paged extras on top of the dense fields."""
        d = super().describe()
        d.update({"engine": "paged", "block_size": self.block_size,
                  "num_blocks": self.block_pool.num_blocks,
                  "prefill_chunk_len": self.prefill_chunk_len,
                  "prefix_sharing": self.prefix_sharing,
                  "paged_kernel": self.paged_kernel})
        return d

    # --------------------------------------------------------- admission
    def validate_prompt(self, prompt):
        """Chunked prefill removes the dense bucket limit: any prompt
        that fits the horizon (with one position to decode into) and the
        pool's total capacity is admissible."""
        n = len(prompt)
        if n + 1 > self.max_len:
            return (f"prompt length {n} leaves no room to decode under "
                    f"max_len {self.max_len}")
        need = (n + 1 + self.block_size - 1) // self.block_size
        if need > self.block_pool.usable:
            return (f"prompt needs {need} KV blocks, pool has only "
                    f"{self.block_pool.usable} usable")
        return None

    def begin_prefill(self, slot, prompt, do_sample=False,
                      temperature=1.0, top_k=0, top_p=1.0,
                      logit_bias=None, dynamic_mask=False):
        """Admit a prompt: match shared prefix blocks, allocate the rest
        (BlockPoolExhausted = capacity, handled by the scheduler as
        queueing pressure, never a request fault), and stage the chunk
        schedule. Chunks fully covered by prefix-cache hits are
        skipped — a fully-cached prompt still runs its LAST chunk, which
        produces the frontier logits (the K/V are cached; the first
        TOKEN never is)."""
        why = self.validate_prompt(prompt)
        if why:
            raise ValueError(why)
        if self.slot_active[slot] or slot in self._pending_prefill:
            raise RuntimeError(f"slot {slot} is busy")
        prompt = [int(t) for t in prompt]
        n, bs = len(prompt), self.block_size
        need = (n + 1 + bs - 1) // bs
        shared, hashes = ([], [])
        if self.prefix_sharing:
            shared, hashes = self.block_pool.match_prefix(prompt)
        try:
            fresh = self.block_pool.alloc(need - len(shared))
        except BaseException:
            # exhaustion AND crash paths (e.g. an injected allocator
            # raise): the matched prefix references must go back, or a
            # failed admission permanently shrinks pool capacity
            self.block_pool.release(shared)
            raise
        if self.prefix_sharing:
            # counted only now, on successful admission — exhaustion
            # retries at the queue head must not inflate the rate
            self.block_pool.count_prefix(len(shared),
                                         n // bs - len(shared))
        blocks = shared + fresh
        self._slot_blocks[slot] = blocks
        self._tables[slot, :] = 0
        self._tables[slot, :len(blocks)] = blocks
        chunk = self.prefill_chunk_len
        start = (len(shared) * bs // chunk) * chunk
        start = min(start, ((n - 1) // chunk) * chunk)
        self._pending_prefill[slot] = {
            "prompt": prompt, "n": n, "next": start,
            "sampling": self._sampling_state(do_sample, temperature,
                                             top_k, top_p, logit_bias,
                                             dynamic_mask),
            "hashes": (self.block_pool.prompt_hashes(prompt)
                       if self.prefix_sharing else []),
            "next_hash": len(shared),
        }

    def prefill_step(self, slot):
        """Run ONE chunk of the slot's staged prompt. Returns the
        request's first generated token when the final chunk ran, None
        while chunks remain (decode waves continue in between)."""
        st = self._pending_prefill[slot]
        if chaos.enabled():
            # host-side, before the donated pool reaches the program — a
            # fired fault leaves device state untouched; the scheduler
            # fails just this request and frees its blocks
            chaos.fire(chaos.PREFILL, slot=slot, chunk_start=st["next"])
        c0, C, n, bs = st["next"], self.prefill_chunk_len, st["n"], \
            self.block_size
        trace = self._slot_trace.get(slot)
        if trace is not None:
            # chunk-indexed progress marker inside the request's PREFILL
            # span: a long chunked admission's folding between decode
            # waves is visible per chunk in the exported trace
            telemetry.trace_instant(
                trace[0], f"PREFILL_CHUNK[{c0 // C}]", pid=trace[1],
                slot=slot, chunk_start=c0, prompt_len=n)
        valid = min(C, n - c0)
        chunk = np.zeros((C,), np.int32)
        chunk[:valid] = st["prompt"][c0:c0 + valid]
        last = c0 + C >= n
        frontier = (n - 1) - c0 if last else 0
        self._key, sub = jax.random.split(self._key)
        sampling = st["sampling"]
        first, self._caches = self._prefill(
            *self._prefill_chunk_args(slot),
            jnp.asarray(self._tables[slot]), jnp.asarray(chunk),
            jnp.int32(c0), jnp.int32(valid), jnp.int32(frontier),
            jnp.asarray(sampling["sample"]),
            jnp.float32(sampling["temp"]),
            jnp.int32(sampling["top_k"]),
            jnp.float32(sampling["top_p"]),
            jnp.asarray(sampling["bias"]), sub)
        # full prompt blocks written by this chunk enter the prefix
        # cache — only now, so a concurrent admission can never share a
        # block whose content is not on the device yet
        if self.prefix_sharing:
            end = c0 + valid
            while (st["next_hash"] < len(st["hashes"])
                   and (st["next_hash"] + 1) * bs <= end):
                i = st["next_hash"]
                self.block_pool.register_hash(self._slot_blocks[slot][i],
                                              st["hashes"][i])
                st["next_hash"] += 1
        st["next"] = c0 + C
        if not last:
            return None
        del self._pending_prefill[slot]
        first = int(np.asarray(first))
        self._arm_slot(slot, first, n, sampling)
        return first

    def _prefill_chunk_args(self, slot):
        """Leading argument tuple of the prefill-chunk program (the
        speculative engine appends its draft params here so ONE chunk
        program writes both models' K/V)."""
        return (self._params, self._buffers, self._caches)

    def prefill_slot(self, slot, prompt, **kw):
        """Synchronous admission (runs every chunk back-to-back) — the
        dense-engine surface, kept for direct engine users; the
        scheduler uses begin_prefill/prefill_step to fold chunks between
        waves. Accepts the full per-request sampling surface
        (do_sample, temperature, top_k, top_p, logit_bias)."""
        self.begin_prefill(slot, prompt, **kw)
        while True:
            first = self.prefill_step(slot)
            if first is not None:
                return first

    # -------------------------------------------------- block-level handoff
    def export_slot_kv(self, slot):
        """Package a prefilled slot's populated KV blocks for a
        block-level handoff to another replica: the allocator manifest
        (BlockPool.export_blocks) plus the per-layer device content
        gathered at the slot's block ids, digest-sealed. The gather is
        its own tiny program (compiled lazily, like the COW copy) —
        tree-generic over the cache bundle, so the speculative engine's
        (target, draft) pools ride the same path with no override.

        The slot itself is left untouched: the caller retires it (which
        frees the blocks but keeps their prefix hashes) only once the
        payload is safely in hand."""
        if not self.slot_active[slot]:
            raise RuntimeError(f"slot {slot} is not active "
                               "(handoff export needs a completed prefill)")
        if slot in self._pending_prefill:
            raise RuntimeError(f"slot {slot} is mid-prefill")
        blocks = list(self._slot_blocks[slot])
        manifest = self.block_pool.export_blocks(blocks)
        if self._handoff_gather_fn is None:
            def gather_fn(caches, idx):
                return [leaf[idx]
                        for leaf in jax.tree_util.tree_leaves(caches)]
            self._handoff_gather_fn = (telemetry.instrument_jit(
                jax.jit(gather_fn), "paged_handoff_gather")
                if self._jit else gather_fn)
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        layers = [np.asarray(x)
                  for x in self._handoff_gather_fn(self._caches, idx)]
        n = int(self.slot_pos[slot])
        payload = {
            "version": HANDOFF_VERSION,
            "n_tokens": n,
            "next_token": int(self.slot_tok[slot]),
            "block_size": self.block_size,
            "blocks": len(blocks),
            "manifest": manifest,
            "layers": layers,
            "nbytes": sum(a.nbytes for a in layers),
            "digest": _handoff_digest(layers, n, self.block_size),
        }
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.hop(kind="kv_export", slot=slot, digest=payload["digest"],
                   blocks=payload["blocks"], nbytes=payload["nbytes"],
                   n_tokens=n)
        return payload

    def import_handoff(self, slot, prompt, payload, do_sample=False,
                       temperature=1.0, top_k=0, top_p=1.0,
                       logit_bias=None, dynamic_mask=False):
        """Admit a request from an exported KV payload: verify the
        digest and geometry (HandoffRefused = request fault — decoding
        over corrupt or misaligned K/V would silently emit wrong
        tokens), allocate local blocks (BlockPoolExhausted = capacity,
        exactly an admission under pool pressure), scatter the content
        in, and arm the slot as if the final prefill chunk had just run
        here. `prompt` is the handed-off request's continuation
        (original prompt + the first token the prefill side produced):
        the slot arms at position len(prompt) - 1 holding prompt[-1],
        and the next decode wave writes that token's K/V — bit-for-bit
        the single-replica schedule. No prefill-chunk program runs (the
        scatter is a separate lazy jit), which is the whole point:
        a handoff costs bytes on the wire, not recompute."""
        why = self.validate_prompt(prompt)
        if why:
            raise ValueError(why)
        if self.slot_active[slot] or slot in self._pending_prefill:
            raise RuntimeError(f"slot {slot} is busy")
        prompt = [int(t) for t in prompt]
        layers = list(payload.get("layers", ()))
        if chaos.enabled():
            # injected wire corruption: flip payload content out from
            # under its digest (host-side copies; the exporter's arrays
            # are untouched) — the digest check below MUST refuse it
            if chaos.value(chaos.HANDOFF_IMPORT, slot=slot,
                           blocks=payload.get("blocks")):
                corrupt = np.array(layers[0])
                corrupt.flat[0] += np.asarray(1, corrupt.dtype)
                layers[0] = corrupt
        if payload.get("version") != HANDOFF_VERSION:
            raise HandoffRefused(
                f"handoff version {payload.get('version')!r} != "
                f"{HANDOFF_VERSION} (mixed-version fleet)")
        if int(payload["block_size"]) != self.block_size:
            raise HandoffRefused(
                f"payload block_size {payload['block_size']} != pool "
                f"block_size {self.block_size}")
        n = int(payload["n_tokens"])
        if n != len(prompt) - 1 or int(payload["next_token"]) != prompt[-1]:
            raise HandoffRefused(
                "payload token state does not match the continuation "
                f"(payload n={n}, next={payload['next_token']}; "
                f"continuation len={len(prompt)})")
        nblk = len(payload["manifest"])
        if nblk * self.block_size < n + 1 or nblk != payload.get("blocks"):
            raise HandoffRefused(
                f"{nblk} exported block(s) cannot back {n} tokens "
                "plus the decode frontier")
        leaves = jax.tree_util.tree_leaves(self._caches)
        if len(layers) != len(leaves) or any(
                a.shape != (nblk,) + l.shape[1:] or a.dtype != l.dtype
                for a, l in zip(layers, leaves)):
            raise HandoffRefused(
                "payload layer layout does not match this engine's "
                "cache bundle (engine-flavor or geometry mismatch)")
        if _handoff_digest(layers, n, self.block_size) != payload["digest"]:
            raise HandoffRefused(
                "handoff digest mismatch: payload content is corrupt")
        fresh = self.block_pool.import_blocks(payload["manifest"])
        try:
            if self._handoff_scatter_fn is None:
                def scatter_fn(caches, idx, data):
                    flat, treedef = jax.tree_util.tree_flatten(caches)
                    return jax.tree_util.tree_unflatten(
                        treedef,
                        [leaf.at[idx].set(arr)
                         for leaf, arr in zip(flat, data)])
                self._handoff_scatter_fn = (telemetry.instrument_jit(
                    jax.jit(scatter_fn, donate_argnums=(0,)),
                    "paged_handoff_scatter")
                    if self._jit else scatter_fn)
            idx = jnp.asarray(np.asarray(fresh, np.int32))
            self._caches = self._handoff_scatter_fn(self._caches, idx,
                                                    layers)
            self._slot_blocks[slot] = fresh
            self._tables[slot, :] = 0
            self._tables[slot, :len(fresh)] = fresh
            if self.prefix_sharing:
                # content is on the device NOW — full prompt blocks may
                # enter the prefix cache (first writer wins), so the
                # decode replica's follow-up admissions share them
                for i, h in enumerate(self.block_pool.prompt_hashes(
                        prompt[:n])[:len(fresh)]):
                    self.block_pool.register_hash(fresh[i], h)
        except BaseException:
            self.block_pool.release(fresh)
            self._slot_blocks[slot] = []
            self._tables[slot, :] = 0
            raise
        first = prompt[-1]
        self._arm_slot(slot, first, n,
                       self._sampling_state(do_sample, temperature, top_k,
                                            top_p, logit_bias,
                                            dynamic_mask))
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.hop(kind="kv_import", slot=slot, digest=payload["digest"],
                   blocks=nblk, nbytes=payload.get("nbytes"), n_tokens=n)
        return first

    # ------------------------------------------------------------- waves
    def _prepare_wave(self, active_now):
        """Back each active lane's next write position with a block.
        Allocation failure excludes the lane from this wave (its table
        row still maps unallocated entries to scratch, so the frozen
        lane's in-program write is harmless) and reports it for
        preemption. A shared write target (safety net — full-block
        sharing keeps the frontier private by construction) is
        copy-on-write'd first."""
        starved = []
        for s, live in enumerate(active_now):
            if not live:
                continue
            bi = self.slot_pos[s] // self.block_size
            blocks = self._slot_blocks[s]
            try:
                if bi >= len(blocks):
                    blk, = self.block_pool.alloc(1)
                    blocks.append(blk)
                    self._tables[s, bi] = blk
                elif self.block_pool.refcount(blocks[bi]) > 1:
                    self._ensure_private(s, bi)
            except BlockPoolExhausted:
                starved.append(s)
                active_now[s] = False
        self.last_starved_slots = starved
        return active_now

    def _wave_args(self, active_now, poison, key):
        # the program scatters EVERY lane's K/V unconditionally (fixed
        # shapes); a lane not in THIS wave (free, mid-prefill, starved)
        # would write its stale token through its table row into a live
        # block — a mid-chunked-prefill slot's table is already
        # populated, possibly with SHARED blocks. Upload scratch rows
        # for those lanes so the write lands in block 0 by design.
        tables = np.where(np.asarray(active_now, bool)[:, None],
                          self._tables, np.int32(BlockPool.SCRATCH))
        return (self._params, self._buffers, self._caches,
                jnp.asarray(tables),
                jnp.asarray(self.slot_tok, jnp.int32),
                jnp.asarray(self.slot_pos, jnp.int32),
                jnp.asarray(active_now, bool),
                *self._sampling_args(),
                jnp.asarray(poison), key)

    # ----------------------------------------------------- copy-on-write
    def _ensure_private(self, slot, bi):
        """Give the slot a private copy of table entry `bi` (the pool
        moves the reference; the device content is copied by a tiny
        jitted program, compiled lazily — the normal flow never diverges
        into a shared block, so this almost never runs)."""
        blocks = self._slot_blocks[slot]
        blk = blocks[bi]
        new = self.block_pool.cow(blk)
        if new == blk:
            return
        self._caches = self._copy_block(self._caches, blk, new)
        blocks[bi] = new
        self._tables[slot, bi] = new

    def _copy_block(self, caches, src, dst):
        if self._copy_fn is None:
            def copy_fn(caches, src, dst):
                return [(ck.at[dst].set(ck[src]), cv.at[dst].set(cv[src]))
                        for ck, cv in caches]
            self._copy_fn = (telemetry.instrument_jit(
                jax.jit(copy_fn, donate_argnums=(0,)), "paged_cow_copy")
                if self._jit else copy_fn)
        return self._copy_fn(caches, jnp.int32(src), jnp.int32(dst))

    # ------------------------------------------------------------- slots
    def retire_slot(self, slot):
        """Free the slot AND its blocks. Freed blocks keep their prefix
        hashes (lazy eviction), so a follow-up request with the same
        prompt — or this request re-admitted after preemption — re-hits
        the cache instead of recomputing."""
        super().retire_slot(slot)
        blocks = self._slot_blocks[slot]
        if blocks:
            self.block_pool.release(blocks)
        self._slot_blocks[slot] = []
        self._tables[slot, :] = 0

    def _health(self):
        # cache_blocks_used/total mirror the gauges of the same name:
        # the fleet router (and any LB) reads pool pressure from ONE
        # /healthz fetch instead of scraping /metrics
        h = super()._health()
        h.update(block_size=self.block_size,
                 paged_kernel=self.paged_kernel,
                 cache_blocks_used=self.block_pool.used,
                 cache_blocks_total=self.block_pool.usable,
                 prefix_cache_hits=self.block_pool.prefix_hits,
                 prefix_cache_misses=self.block_pool.prefix_misses)
        return h


def _spec_verify_tail(lo, tok, pos, active, sample, temps, top_k, top_p,
                      bias, spec_len, draft_toks, draft_probs, poison,
                      key):
    """The speculative wave's acceptance–rejection tail: the
    _select_wave_tokens math applied position-by-position over the
    verify chunk's [S, C, V] target logits (C = k + 1), with EXACT
    acceptance–rejection so the output distribution equals the target
    model's own — and the greedy path is bitwise the target trajectory.

    Greedy lanes accept the longest draft prefix agreeing with the
    target argmax (over BIASED logits, like the non-speculative tail)
    and emit the correcting argmax at the first mismatch. Sampled lanes
    accept draft token d_i with probability min(1, p_t(d_i)/p_d(d_i))
    and resample the first rejection from the normalized residual
    max(p_t - p_d, 0); with all k accepted, the bonus token is the
    a == k case of the same formula because p_d is zero-extended at
    position k (residual = p_t). Both p_t and p_d are the PROCESSED
    distributions (temperature, top-k/top-p, logit-bias applied), so
    the scenario surface composes with speculation exactly.

    Per-lane spec_len clamps acceptance (horizon, dynamic token-mask
    lanes run at spec_len 0 == plain decode). Frozen lanes (inactive,
    poisoned, non-finite) emit 0 tokens and keep their position — the
    scheduler retires poisoned lanes exactly like the non-spec wave."""
    s, c, v = lo.shape
    k = c - 1
    lo = jnp.where(poison[:, None, None], jnp.float32(jnp.nan),
                   lo + bias[:, None, :])
    finite = jnp.all(jnp.isfinite(lo), axis=(1, 2))
    greedy = jnp.argmax(lo, axis=-1).astype(jnp.int32)          # [S, C]
    scaled = lo / jnp.maximum(temps, 1e-6)[:, None, None]
    filt = _filter_top_k_top_p(
        scaled.reshape(s * c, v), jnp.repeat(top_k, c),
        jnp.repeat(top_p, c)).reshape(s, c, v)
    p_t = jax.nn.softmax(filt, axis=-1)                         # [S, C, V]
    valid = jnp.arange(k)[None, :] < spec_len[:, None]          # [S, k]
    ok_greedy = draft_toks == greedy[:, :k]
    key_u, key_r, key_f = jax.random.split(key, 3)
    u = jax.random.uniform(key_u, (s, k))
    pt_d = jnp.take_along_axis(p_t[:, :k, :], draft_toks[..., None],
                               axis=-1)[..., 0]                 # [S, k]
    pd_d = jnp.take_along_axis(draft_probs, draft_toks[..., None],
                               axis=-1)[..., 0]
    ok_sample = u * pd_d < pt_d
    ok = jnp.where(sample[:, None], ok_sample, ok_greedy) & valid
    accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    a = jnp.sum(accepted, axis=1)                    # [S] in [0, k]
    # the one non-draft token per lane: correction at the rejection,
    # bonus past a fully-accepted span. p_d is zeroed at every position
    # the lane did NOT draft (i >= its spec_len, the k-th position
    # included) — there the formula must degenerate to sampling p_t
    # itself: a horizon- or token-mask-clamped lane proposed nothing at
    # its frontier, and subtracting a draft distribution it never
    # offered would skew the output away from the target's (the
    # "spec_len 0 == plain decode" exactness contract)
    p_d_ext = jnp.concatenate(
        [draft_probs, jnp.zeros((s, 1, v), draft_probs.dtype)], axis=1)
    p_d_ext = jnp.where(
        (jnp.arange(c)[None, :] < spec_len[:, None])[:, :, None],
        p_d_ext, 0.0)
    p_t_a = jnp.take_along_axis(p_t, a[:, None, None], axis=1)[:, 0]
    p_d_a = jnp.take_along_axis(p_d_ext, a[:, None, None], axis=1)[:, 0]
    residual = jnp.maximum(p_t_a - p_d_a, 0.0)
    res_tok = jax.random.categorical(
        key_r, jnp.log(jnp.maximum(residual, 1e-30)),
        axis=-1).astype(jnp.int32)
    # float round-off can zero a residual row that is positive in exact
    # arithmetic — fall back to the target distribution itself (a
    # measure-zero correction, never reached in exact math)
    fallback = jax.random.categorical(
        key_f, jnp.log(jnp.maximum(p_t_a, 1e-30)),
        axis=-1).astype(jnp.int32)
    res_tok = jnp.where(jnp.sum(residual, axis=-1) > 0, res_tok,
                        fallback)
    greedy_a = jnp.take_along_axis(greedy, a[:, None], axis=1)[:, 0]
    extra = jnp.where(sample, res_tok, greedy_a).astype(jnp.int32)
    draft_pad = jnp.concatenate(
        [draft_toks, jnp.zeros((s, 1), jnp.int32)], axis=1)
    out = jnp.where(jnp.arange(c)[None, :] < a[:, None], draft_pad,
                    extra[:, None])
    ok_lane = active & finite
    n_emit = jnp.where(ok_lane, a + 1, 0)
    new_pos = pos + n_emit
    nxt = jnp.where(ok_lane, extra, tok)
    return out, n_emit, nxt, new_pos, finite


class SpeculativePagedEngine(PagedServingEngine):
    """Draft-k / verify-once speculative decoding over the paged engine.

    A small DRAFT model proposes up to k tokens per slot per wave; the
    target model scores all k + 1 positions in ONE batched forward built
    on `chunk_attention` over the SAME block tables (C == k + 1 — the
    C == 1 case of the verify kernel IS the plain decode wave, so this
    is a third compiled program, not a new attention path). Exact
    acceptance–rejection (see `_spec_verify_tail`) keeps outputs
    distribution-identical to the target model — bitwise-identical under
    greedy — while a wave advances each lane by 1..k+1 tokens: decode
    rounds per generated token drop by the acceptance rate.

    Memory discipline: the draft model's paged KV pools share the block
    TABLES (and therefore the allocator, refcounts, prefix sharing and
    copy-on-write) with the target pools — one block id names the same
    token span in both. The prefill-chunk program writes BOTH models'
    K/V, so a prefix-cache hit serves the draft cache too, and
    `retire_slot` frees both at once. Speculated-ahead blocks that the
    acceptance did not commit are rolled back after every wave
    (`_rollback_spec_blocks`) — the pool never holds blocks for tokens
    that were rejected.

    Compile-once holds as THREE programs with fully static shapes:
    `paged_spec_draft_wave` (k+1 draft decode steps in one executable),
    `paged_spec_verify` (the chunk-scored target forward + acceptance
    tail), and `paged_spec_prefill_chunk` (target + draft chunk
    prefill). Per-lane spec_len (horizon clamp, dynamic token-mask
    lanes) is a traced VALUE, not a shape.
    """

    def __init__(self, model, draft_model, spec_k=4, **kw):
        if draft_model is None:
            raise ValueError("SpeculativePagedEngine needs a draft_model")
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.spec_k = int(spec_k)
        draft_model.eval()
        self.draft_model = draft_model
        self._draft_params, self._draft_buffers = \
            draft_model.functional_state()
        if int(draft_model.cfg.vocab_size) != int(model.cfg.vocab_size):
            raise ValueError(
                f"draft vocab {draft_model.cfg.vocab_size} != target "
                f"vocab {model.cfg.vocab_size}: acceptance-rejection "
                "compares distributions over ONE vocabulary")
        self._wave_spec_len = None
        self.last_spec_proposed = 0
        self.last_spec_accepted = 0
        super().__init__(model, **kw)

    def describe(self):
        d = super().describe()
        d.update({"engine": "spec_paged", "spec_k": self.spec_k})
        return d

    # ---------------------------------------------------------- caches
    def _make_caches(self):
        # ONE bundle, donated through every program: the target pools
        # and the draft pools ride together so each program updates its
        # half in place and passes the other through aliased
        tgt = super()._make_caches()
        draft = self.draft_model.init_paged_cache(
            self.block_pool.num_blocks, self.block_size, self.max_len,
            dtype=self.cache_dtype)
        return (tgt, draft)

    # -------------------------------------------------------- programs
    def _build_programs(self):
        model, draft, k = self.model, self.draft_model, self.spec_k
        kern = self.paged_kernel

        def draft_wave(dp, db, caches, tables, tok, pos, sample,
                       temps, top_k, top_p, bias, spec_len, key):
            """k+1 draft decode steps in ONE executable: step j writes
            the fed token's K/V at pos+j and proposes the next; the
            final step is write-only (it commits d_k's K/V so a fully
            accepted span leaves the draft cache synchronized). Writes
            past a lane's spec_len land in the scratch block via a
            scratch table row — per-step, per-lane, still one program."""
            tgt_caches, dr_caches = caches
            cur = tok
            toks, probs = [], []
            for j in range(k + 1):
                tab_j = jnp.where((j <= spec_len)[:, None], tables,
                                  jnp.int32(BlockPool.SCRATCH))
                with paged_attention.kernel_scope(kern):
                    out, _ = draft.functional_call(
                        dp, db, cur[:, None], dr_caches, pos + j,
                        method="decode_step", block_tables=tab_j)
                logits, dr_caches = out
                if j == k:
                    break               # write-only step: no proposal
                lo = _raw(logits)[:, 0, :].astype(jnp.float32) + bias
                greedy = jnp.argmax(lo, axis=-1).astype(jnp.int32)
                scaled = lo / jnp.maximum(temps, 1e-6)[:, None]
                filt = _filter_top_k_top_p(scaled, top_k, top_p)
                key, sub = jax.random.split(key)
                sampled = jax.random.categorical(
                    sub, filt, axis=-1).astype(jnp.int32)
                cur = jnp.where(sample, sampled, greedy)
                toks.append(cur)
                probs.append(jax.nn.softmax(filt, axis=-1))
            return (jnp.stack(toks, axis=1), jnp.stack(probs, axis=1),
                    (tgt_caches, dr_caches))

        def spec_verify(p, b, caches, tables, tok, pos, active, sample,
                        temps, top_k, top_p, bias, spec_len, draft_toks,
                        draft_probs, poison, key):
            """Verify-once: ONE target forward scores all k+1 positions
            of every lane (decode_chunk == chunk_attention over the
            block tables), then the exact acceptance-rejection tail."""
            tgt_caches, dr_caches = caches
            chunk = jnp.concatenate([tok[:, None], draft_toks], axis=1)
            with paged_attention.kernel_scope(kern):
                out, _ = model.functional_call(
                    p, b, chunk, tgt_caches, tables, pos, spec_len + 1,
                    method="decode_chunk")
            logits, tgt_caches = out
            lo = _raw(logits).astype(jnp.float32)       # [S, k+1, V]
            out_toks, n_emit, nxt, new_pos, finite = _spec_verify_tail(
                lo, tok, pos, active, sample, temps, top_k, top_p, bias,
                spec_len, draft_toks, draft_probs, poison, key)
            return (out_toks, n_emit, nxt, new_pos, finite,
                    (tgt_caches, dr_caches))

        def prefill_chunk(p, b, caches, dp, db, table, chunk,
                          chunk_start, valid_len, frontier, sample, temp,
                          top_k, top_p, bias, key):
            """The spec configuration's ONE prefill program: the chunk
            writes the TARGET pools (frontier logits select the first
            token, exactly the non-spec chunk) AND the DRAFT pools — a
            draft cache synchronized at admission is what lets the
            first decode wave start drafting immediately, and a
            prefix-cache hit skips the chunk for both models at once."""
            tgt_caches, dr_caches = caches
            with paged_attention.kernel_scope(kern):
                out, _ = model.functional_call(
                    p, b, chunk[None, :], tgt_caches,
                    method="prefill_chunk", block_tables=table[None, :],
                    chunk_start=chunk_start, valid_len=valid_len,
                    frontier=frontier)
                logits, tgt_caches = out
                dout, _ = draft.functional_call(
                    dp, db, chunk[None, :], dr_caches,
                    method="prefill_chunk", block_tables=table[None, :],
                    chunk_start=chunk_start, valid_len=valid_len,
                    frontier=frontier)
            _, dr_caches = dout         # draft frontier logits unused
            lo = _raw(logits)[0, 0].astype(jnp.float32)
            first = _select_first_token(lo, sample, temp, top_k, top_p,
                                        bias, key)
            return first, (tgt_caches, dr_caches)

        self._draft_wave_fn = draft_wave
        self._decode_wave_fn = spec_verify
        self._prefill_fn = prefill_chunk
        self._program_donate_argnums = (2,)

        if self._jit:
            self._draft_wave = telemetry.instrument_jit(
                jax.jit(draft_wave,
                        donate_argnums=self._program_donate_argnums),
                "paged_spec_draft_wave")
            self._decode_wave = telemetry.instrument_jit(
                jax.jit(spec_verify,
                        donate_argnums=self._program_donate_argnums),
                "paged_spec_verify")
            self._prefill = telemetry.instrument_jit(
                jax.jit(prefill_chunk,
                        donate_argnums=self._program_donate_argnums),
                "paged_spec_prefill_chunk")
        else:
            self._draft_wave = draft_wave
            self._decode_wave = spec_verify
            self._prefill = prefill_chunk

    @property
    def draft_compiles(self):
        """Compiled draft-wave programs (compile-once: stays 1)."""
        return self._draft_wave._cache_size() if self._jit else 0

    def _copy_block(self, caches, src, dst):
        """COW over the BUNDLE: a shared block's content must be copied
        in the target AND draft pools — one block id names the same
        token span in both, so a half-copied block would desynchronize
        the draft cache from the tokens it claims to hold."""
        if self._copy_fn is None:
            def copy_fn(caches, src, dst):
                tgt, dr = caches

                def cp(pools):
                    return [(ck.at[dst].set(ck[src]),
                             cv.at[dst].set(cv[src])) for ck, cv in pools]
                return (cp(tgt), cp(dr))
            self._copy_fn = (telemetry.instrument_jit(
                jax.jit(copy_fn, donate_argnums=(0,)), "paged_cow_copy")
                if self._jit else copy_fn)
        return self._copy_fn(caches, jnp.int32(src), jnp.int32(dst))

    def _prefill_chunk_args(self, slot):
        return (self._params, self._buffers, self._caches,
                self._draft_params, self._draft_buffers)

    # ----------------------------------------------------------- waves
    def _prepare_wave(self, active_now):
        """Back every position the wave may write — pos .. pos+spec_len
        per lane (draft writes + the verify chunk's span) — with
        allocated, exclusively-owned blocks. Allocation is atomic per
        lane; a lane that cannot get its full span is starved out of
        the wave and preempted by recompute, exactly like the
        single-token engine."""
        starved, bs = [], self.block_size
        for s, live in enumerate(active_now):
            if not live:
                continue
            last_bi = (self.slot_pos[s] + self._wave_spec_len[s]) // bs
            blocks = self._slot_blocks[s]
            try:
                missing = last_bi + 1 - len(blocks)
                if missing > 0:
                    for blk in self.block_pool.alloc(missing):
                        blocks.append(blk)
                        self._tables[s, len(blocks) - 1] = blk
                for bi in range(self.slot_pos[s] // bs, last_bi + 1):
                    if self.block_pool.refcount(blocks[bi]) > 1:
                        self._ensure_private(s, bi)
            except BlockPoolExhausted:
                starved.append(s)
                active_now[s] = False
        self.last_starved_slots = starved
        return active_now

    def _rollback_spec_blocks(self, wave_slots):
        """Return speculated-ahead blocks the acceptance did not commit:
        after the wave, a lane needs exactly the blocks covering its
        committed positions [0, pos) — anything past that was allocated
        for rejected draft tokens and goes straight back to the pool
        (refcount-clean: fresh spec blocks are never hashed and never
        shared). Skipping this (the chaos no-rollback control) leaves
        the pool holding blocks for tokens that never existed."""
        bs = self.block_size
        for s in wave_slots:
            blocks = self._slot_blocks[s]
            needed = max(1, (self.slot_pos[s] + bs - 1) // bs)
            if len(blocks) > needed:
                extra = blocks[needed:]
                del blocks[needed:]
                self._tables[s, needed:] = 0
                self.block_pool.release(extra)

    def decode_wave(self):
        """One speculative wave: draft k, verify once, accept exactly.
        Returns {slot: [tokens]} — 1..k+1 tokens per healthy lane (the
        scheduler streams them in order and retires mid-batch on
        eos/budget/stop). Poisoned/non-finite lanes emit nothing, are
        listed in `last_nonfinite_slots`, and their speculation is
        rolled back with the rest."""
        active_now = list(self.slot_active)
        if not any(active_now):
            self.last_nonfinite_slots = []
            self.last_starved_slots = []
            return {}
        if chaos.enabled():
            chaos.fire(chaos.DECODE_WAVE, active=sum(active_now))
        # per-lane draft span: the horizon clamps it (writes stop at
        # max_len - 1), a dynamic token-mask lane runs at 0 — the
        # verify chunk then degenerates to the plain single-token wave
        # for that lane, mask applied, same program
        spec_len = [0] * self.num_slots
        for s, live in enumerate(active_now):
            if live:
                limit = self.max_len - 1 - self.slot_pos[s]
                want = 0 if self.slot_dynamic_mask[s] else self.spec_k
                spec_len[s] = max(0, min(want, limit))
        self._wave_spec_len = spec_len
        active_now = self._prepare_wave(active_now)
        if not any(active_now):
            self.last_nonfinite_slots = []
            return {}
        poison = np.zeros((self.num_slots,), bool)
        if chaos.enabled():
            hit = chaos.value(chaos.DECODE_WAVE_NAN)
            if hit is not None:
                for s in np.atleast_1d(hit):
                    poison[int(s)] = True
        self._key, dkey = jax.random.split(self._key)
        self._key, vkey = jax.random.split(self._key)
        tables = jnp.asarray(
            np.where(np.asarray(active_now, bool)[:, None], self._tables,
                     np.int32(BlockPool.SCRATCH)))
        tok = jnp.asarray(self.slot_tok, jnp.int32)
        pos = jnp.asarray(self.slot_pos, jnp.int32)
        act = jnp.asarray(active_now, bool)
        sampling = self._sampling_args()
        sl = jnp.asarray(spec_len, jnp.int32)
        # the draft wave takes no active mask: inactive lanes ride
        # scratch table rows and their proposals are discarded by
        # the verify tail's active where — one argument fewer keeps
        # every draft input live for the donation audit
        draft_toks, draft_probs, self._caches = self._draft_wave(
            self._draft_params, self._draft_buffers, self._caches,
            tables, tok, pos, *sampling, sl, dkey)
        out_toks, n_emit, nxt, new_pos, finite, self._caches = \
            self._decode_wave(
                self._params, self._buffers, self._caches, tables, tok,
                pos, act, *sampling, sl, draft_toks, draft_probs,
                jnp.asarray(poison), vkey)
        out_toks = np.asarray(out_toks)
        n_emit = np.asarray(n_emit)
        nxt = np.asarray(nxt)
        new_pos = np.asarray(new_pos)
        finite = np.asarray(finite)
        out, bad, waved = {}, [], []
        proposed = accepted = 0
        for s, was_active in enumerate(active_now):
            if not was_active:
                continue
            waved.append(s)
            if not bool(finite[s]):
                bad.append(s)       # lane frozen in-program; caller
                continue            # must retire it before the next wave
            n = int(n_emit[s])
            proposed += spec_len[s]
            accepted += n - 1       # the extra token is never a draft's
            self.slot_pos[s] = int(new_pos[s])
            self.slot_tok[s] = int(nxt[s])
            out[s] = [int(t) for t in out_toks[s, :n]]
        self.last_nonfinite_slots = bad
        self.last_spec_proposed = proposed
        self.last_spec_accepted = accepted
        # rejected-token blocks go back NOW, poisoned lanes included —
        # the pool must never hold blocks for tokens that don't exist
        self._rollback_spec_blocks(waved)
        return out

    def _health(self):
        h = super()._health()
        h.update(speculative=True, spec_k=self.spec_k,
                 draft_compiles=self.draft_compiles)
        return h
