"""Scheduler: admission queue + continuous-batching loop on top of
ServingEngine.

FCFS admission: whenever a slot is free and the queue is non-empty, the
head request is prefilled into the slot MID-STREAM — the other slots'
in-flight decodes are untouched (next wave simply sees one more active
lane; same compiled program). Retirement (EOS / max_tokens / cache
horizon / timeout) frees slots between waves and the freed slot is
refilled in the same step() — a slot never idles while work is queued.

Thread-model: submit() is safe from any producer thread (the bench
script's Poisson arrival generator); the wave loop itself runs wherever
run()/step() is called — the engine's compiled programs are driven from
one thread at a time.
"""
import collections
import threading
import time

from ..utils import profiler
from ..utils.profiler import RecordEvent
from .metrics import ServingMetrics
from .request import Request, RequestState


class Scheduler:
    def __init__(self, engine, max_queue=None, completed_log=1024):
        self.engine = engine
        self.max_queue = max_queue
        self._queue = collections.deque()
        self._lock = threading.Lock()
        self._slot_req = [None] * engine.num_slots
        self.metrics = ServingMetrics(engine.num_slots)
        # bounded: callers hold their own Request handles (submit returns
        # them); this ring is a debugging/inspection tail, and unbounded
        # growth would leak every prompt ever served on a long-running
        # server. completed_log=None keeps everything (tests/benches).
        self.completed = collections.deque(maxlen=completed_log)

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        """Enqueue a Request (or build one from kwargs: prompt,
        max_tokens, eos_token_id, timeout, on_token, do_sample,
        temperature). Oversized prompts are rejected CLEANLY here — the
        request is marked REJECTED, a ValueError raises to the caller,
        and the engine/queue state is untouched."""
        if request is None:
            request = Request(**kw)
        why = self.engine.validate_prompt(request.prompt)
        if why is not None:
            self.metrics.on_reject()
            request._reject(why)           # raises ValueError
        with self._lock:
            if self.max_queue is not None and len(self._queue) >= \
                    self.max_queue:
                self.metrics.on_reject()
                request._reject(f"queue full (max_queue={self.max_queue})")
            request._mark_submitted()
            self._queue.append(request)
            depth = len(self._queue)
        self.metrics.on_submit()
        self.metrics.on_queue_depth(depth)
        return request

    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def _pop_next(self):
        with self._lock:
            req = self._queue.popleft() if self._queue else None
            depth = len(self._queue)
        self.metrics.on_queue_depth(depth)
        return req

    def _admit(self):
        """Prefill queued requests into free slots. A request whose
        timeout already expired in the queue is retired without spending
        a prefill on it."""
        while True:
            free = self.engine.free_slots()
            if not free:
                return
            req = self._pop_next()
            if req is None:
                return
            if req._timed_out():
                req._finish("timeout")
                self._complete(req)
                continue
            slot = free[0]
            req._start_prefill(slot)
            self._slot_req[slot] = req
            with RecordEvent("serving/prefill"):
                first = self.engine.prefill_slot(
                    slot, req.prompt, do_sample=req.do_sample,
                    temperature=req.temperature)
            self.metrics.on_prefill()
            req._emit(first)
            self.metrics.on_token(time.monotonic())
            self._maybe_retire(slot, first)

    # ---------------------------------------------------------- wave loop
    def _maybe_retire(self, slot, last_token):
        """Retire the slot if its request just finished: EOS (even on the
        very first prefill-produced token), token budget, cache horizon,
        or wall-clock timeout."""
        req = self._slot_req[slot]
        reason = None
        if req.eos_token_id is not None and last_token == req.eos_token_id:
            reason = "eos"
        elif len(req.output_tokens) >= req.max_tokens:
            reason = "max_tokens"
        elif self.engine.slot_full(slot):
            reason = "length"
        elif req._timed_out():
            reason = "timeout"
        if reason is not None:
            self.engine.retire_slot(slot)
            self._slot_req[slot] = None
            req._finish(reason)
            self._complete(req)

    def _complete(self, req):
        self.completed.append(req)
        self.metrics.on_complete(req)

    def step(self):
        """One scheduling round: refill free slots from the queue, run
        one batched decode wave, stream the tokens, retire finished
        slots. Returns the number of requests still in flight or queued."""
        self._admit()
        active = self.engine.active_slots()
        if active:
            with RecordEvent("serving/decode_wave"):
                toks = self.engine.decode_wave()
            self.metrics.on_wave(len(active))
            now = time.monotonic()
            for slot, tok in toks.items():
                self._slot_req[slot]._emit(tok)
                self.metrics.on_token(now)
                self._maybe_retire(slot, tok)
        # chrome-trace counter track: occupancy/queue depth over time,
        # on the same timeline as the decode-wave slices
        if profiler.trace_enabled():
            profiler.emit_trace_event({
                "ph": "C", "name": "serving/slots", "cat": "serving",
                "args": {"active": self.in_flight(),
                         "queued": self.queue_depth()}})
        return self.in_flight() + self.queue_depth()

    def in_flight(self):
        return sum(1 for r in self._slot_req if r is not None)

    def run(self, drain=True, max_waves=None):
        """Drive step() until the queue and all slots drain (or max_waves
        hit). Producer threads may keep submit()ing while this runs."""
        waves = 0
        while self.step():
            waves += 1
            if max_waves is not None and waves >= max_waves:
                break
        return waves

    # ---------------------------------------------------------- conveniences
    def generate(self, prompt, **kw):
        """Blocking single-request convenience (the create_llm_predictor
        surface): submit, drain, return the generated token list."""
        req = self.submit(prompt=prompt, **kw)
        while not req.done:
            self.step()
        return req.output_tokens
