"""Scheduler: admission queue + continuous-batching loop on top of
ServingEngine.

FCFS admission: whenever a slot is free and the queue is non-empty, the
head request is prefilled into the slot MID-STREAM — the other slots'
in-flight decodes are untouched (next wave simply sees one more active
lane; same compiled program). Retirement (EOS / max_tokens / cache
horizon / timeout) frees slots between waves and the freed slot is
refilled in the same step() — a slot never idles while work is queued.

Resilience (docs/serving.md "Resilience"; every path below is proven
by injection in scripts/chaos_serving.py):

  * a failed prefill or a non-finite decode lane resolves ONLY that
    request (finish_reason "error") — the rest of the batch keeps
    decoding the same compiled program; a streak of
    `prefill_fail_limit` CONSECUTIVE prefill failures across distinct
    requests escalates to graceful degradation, so a persistently
    broken engine cannot hide behind per-request isolation with
    /healthz still reporting "ok";
  * a decode-wave exception is retried up to `wave_retries` times with
    bounded exponential backoff (`retry_backoff_s`, doubling); an
    exhausted budget degrades the engine gracefully — in-flight
    requests resolve with "error", queued and new work is shed with
    "rejected", /healthz reports "degraded" — instead of a stack trace
    out of the wave loop;
  * admission control: `max_queue` bounds the queue (overflow sheds
    with finish_reason "rejected"), `drain()` stops admissions while
    accepted work runs to completion (/healthz: "draining").

Thread-model: submit() is safe from any producer thread (the bench
script's Poisson arrival generator); the wave loop itself runs wherever
run()/step() is called — the engine's compiled programs are driven from
one thread at a time.
"""
import collections
import threading
import time

from ..utils import flight_recorder, profiler
from ..utils.profiler import RecordEvent
from .metrics import ServingMetrics
from .request import Request, RequestState


class Scheduler:
    def __init__(self, engine, max_queue=None, completed_log=1024,
                 wave_retries=3, retry_backoff_s=0.05,
                 prefill_fail_limit=None):
        self.engine = engine
        self.max_queue = max_queue
        self.wave_retries = max(0, int(wave_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        # consecutive DISTINCT-request prefill failures tolerated before
        # concluding the fault is the engine's, not the requests' (e.g. a
        # raise from inside the compiled prefill after the donated cache
        # was consumed fails every admission thereafter) — reaching it
        # degrades instead of failing requests one-by-one forever while
        # /healthz keeps saying "ok"
        self.prefill_fail_limit = (engine.num_slots + self.wave_retries
                                   if prefill_fail_limit is None
                                   else max(1, int(prefill_fail_limit)))
        self._prefill_fail_streak = 0
        self._queue = collections.deque()
        self._lock = threading.Lock()        # queue + lifecycle flags
        self._wave_lock = threading.Lock()   # one step() at a time
        self._slot_req = [None] * engine.num_slots
        self._draining = False
        self._degraded = False
        self.last_error = None
        self.metrics = ServingMetrics(engine.num_slots)
        # bounded: callers hold their own Request handles (submit returns
        # them); this ring is a debugging/inspection tail, and unbounded
        # growth would leak every prompt ever served on a long-running
        # server. completed_log=None keeps everything (tests/benches).
        self.completed = collections.deque(maxlen=completed_log)

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        """Enqueue a Request (or build one from kwargs: prompt,
        max_tokens, eos_token_id, timeout, on_token, do_sample,
        temperature). Oversized prompts are rejected CLEANLY here — the
        request is marked REJECTED, a ValueError raises to the caller,
        and the engine/queue state is untouched."""
        if request is None:
            request = Request(**kw)
        why = self.engine.validate_prompt(request.prompt)
        if why is not None:
            self.metrics.on_reject()
            request._reject(why)           # raises ValueError
        with self._lock:
            if self._degraded:
                shed = f"engine degraded ({self.last_error})"
            elif self._draining:
                shed = "engine draining (graceful shutdown)"
            elif self.max_queue is not None and len(self._queue) >= \
                    self.max_queue:
                shed = f"queue full (max_queue={self.max_queue})"
            else:
                shed = None
                request._mark_submitted()
                self._queue.append(request)
                depth = len(self._queue)
        if shed is not None:
            self.metrics.on_reject()
            request._reject(shed)          # raises ValueError
        self.metrics.on_submit()
        self.metrics.on_queue_depth(depth)
        return request

    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def _pop_next(self):
        with self._lock:
            req = self._queue.popleft() if self._queue else None
            depth = len(self._queue)
        self.metrics.on_queue_depth(depth)
        return req

    def _admit(self):
        """Prefill queued requests into free slots. A request whose
        timeout already expired in the queue is retired without spending
        a prefill on it."""
        while True:
            free = self.engine.free_slots()
            if not free:
                return
            req = self._pop_next()
            if req is None:
                return
            if req._timed_out():
                req._finish("timeout")
                self._complete(req)
                continue
            slot = free[0]
            req._start_prefill(slot)
            self._slot_req[slot] = req
            try:
                with RecordEvent("serving/prefill"):
                    first = self.engine.prefill_slot(
                        slot, req.prompt, do_sample=req.do_sample,
                        temperature=req.temperature)
            except Exception as e:   # noqa: BLE001 — fault barrier:
                # isolate the failing admission to ITS request; the
                # engine mutates nothing before dispatch, so the slot
                # is still free and every other lane is untouched
                self._slot_req[slot] = None
                self.last_error = e
                self._prefill_fail_streak += 1
                escalate = self._prefill_fail_streak >= \
                    self.prefill_fail_limit
                self._fault("prefill_error",
                            action=("degrade" if escalate
                                    else "request_failed"),
                            request=req, slot=slot, error=e)
                req._fail(e)
                self._complete(req)
                if escalate:
                    self._degrade()
                    return
                continue
            self._prefill_fail_streak = 0
            self.metrics.on_prefill()
            req._emit(first)
            self.metrics.on_token(time.monotonic())
            self._maybe_retire(slot, first)

    # ---------------------------------------------------------- wave loop
    def _maybe_retire(self, slot, last_token):
        """Retire the slot if its request just finished: EOS (even on the
        very first prefill-produced token), token budget, cache horizon,
        or wall-clock timeout."""
        req = self._slot_req[slot]
        reason = None
        if req.eos_token_id is not None and last_token == req.eos_token_id:
            reason = "eos"
        elif len(req.output_tokens) >= req.max_tokens:
            reason = "max_tokens"
        elif self.engine.slot_full(slot):
            reason = "length"
        elif req._timed_out():
            reason = "timeout"
        if reason is not None:
            self.engine.retire_slot(slot)
            self._slot_req[slot] = None
            req._finish(reason)
            self._complete(req)

    def _complete(self, req):
        self.completed.append(req)
        self.metrics.on_complete(req)

    def _fault(self, kind, action=None, request=None, slot=None,
               error=None):
        """One fault handled: count it (serving_faults_total{kind}) and
        journal it through the current flight recorder."""
        self.metrics.on_fault(kind)
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind=kind, action=action,
                      request_id=None if request is None
                      else request.request_id,
                      slot=slot,
                      error=None if error is None else repr(error))

    def _run_wave_with_retry(self):
        """The decode wave behind a bounded-exponential-backoff retry.
        Returns the wave's {slot: token} dict, or None after degrading
        (budget exhausted). The engine raises BEFORE consuming its key
        or the donated cache, so a retried wave replays exactly; an
        error from inside the compiled call may have invalidated the
        donated cache, in which case the retry fails too and the budget
        runs out — degradation, not an infinite loop."""
        delay = self.retry_backoff_s
        for attempt in range(self.wave_retries + 1):
            try:
                with RecordEvent("serving/decode_wave"):
                    return self.engine.decode_wave()
            except Exception as e:   # noqa: BLE001 — fault barrier
                self.last_error = e
                self._fault("wave_error",
                            action=("retry" if attempt < self.wave_retries
                                    else "degrade"),
                            error=e)
                if attempt >= self.wave_retries:
                    break
                self.metrics.on_wave_retry()
                time.sleep(delay)
                delay *= 2
        self._degrade()
        return None

    def _degrade(self):
        """Graceful degradation: the wave loop cannot make progress, so
        resolve everything cleanly — in-flight requests finish with
        "error", queued requests shed with "rejected", new submits are
        rejected, and /healthz reports "degraded" — instead of leaking
        a stack trace through step()."""
        with self._lock:
            # flag + health transition under ONE lock: a concurrent
            # drain() cannot interleave and overwrite "degraded" with
            # "draining" on an engine that can no longer make progress
            self._degraded = True
            self.engine.set_health_state("degraded")
        self._fault("degraded", action="drain_and_reject",
                    error=self.last_error)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self.engine.retire_slot(slot)
            self._slot_req[slot] = None
            req._fail(f"engine degraded: {self.last_error!r}")
            self._complete(req)
        while True:
            req = self._pop_next()
            if req is None:
                break
            self.metrics.on_reject()
            req._reject(f"engine degraded ({self.last_error!r})",
                        raise_error=False)
            # shed, not completed: on_complete would double-count the
            # request and pollute the latency histogram with a
            # queue-wait-only sample — the inspection ring still gets it
            self.completed.append(req)

    def step(self):
        """One scheduling round: refill free slots from the queue, run
        one batched decode wave, stream the tokens, retire finished
        slots. Returns the number of requests still in flight or queued.

        Serialized by `_wave_lock`, so concurrent drivers (a run() loop
        in one thread, shutdown() in another) interleave whole rounds
        instead of racing the engine's donated caches."""
        with self._wave_lock:
            return self._step_locked()

    def _step_locked(self):
        if self._degraded:
            return 0
        self._admit()
        active = self.engine.active_slots()
        if active:
            toks = self._run_wave_with_retry()
            if toks is None:                 # degraded: everything is
                return 0                     # resolved, nothing pending
            self.metrics.on_wave(len(active))
            # fused-sentinel fallout: retire ONLY the poisoned lanes —
            # their requests resolve with "error", healthy neighbours
            # stream on token-identically (proven in chaos_serving)
            for slot in self.engine.last_nonfinite_slots:
                req = self._slot_req[slot]
                self.engine.retire_slot(slot)
                self._slot_req[slot] = None
                self._fault("nonfinite", action="slot_retired",
                            request=req, slot=slot)
                req._fail("non-finite logits in decode wave")
                self._complete(req)
            now = time.monotonic()
            for slot, tok in toks.items():
                self._slot_req[slot]._emit(tok)
                self.metrics.on_token(now)
                self._maybe_retire(slot, tok)
        # chrome-trace counter track: occupancy/queue depth over time,
        # on the same timeline as the decode-wave slices
        if profiler.trace_enabled():
            profiler.emit_trace_event({
                "ph": "C", "name": "serving/slots", "cat": "serving",
                "args": {"active": self.in_flight(),
                         "queued": self.queue_depth()}})
        return self.in_flight() + self.queue_depth()

    def in_flight(self):
        return sum(1 for r in self._slot_req if r is not None)

    @property
    def draining(self):
        return self._draining

    @property
    def degraded(self):
        return self._degraded

    # ------------------------------------------------------- graceful stop
    def drain(self):
        """Stop admitting new work: requests already accepted (queued or
        in a slot) run to completion; new submit()s are shed with
        finish_reason "rejected". /healthz reports "draining". Keep
        driving step()/run() until it returns 0 to finish the accepted
        work."""
        with self._lock:
            self._draining = True
            if not self._degraded:     # degraded is sticky: see _degrade
                self.engine.set_health_state("draining")

    def shutdown(self, max_waves=None):
        """Graceful shutdown: drain(), drive the wave loop until every
        accepted request resolves, then stop the engine's metrics
        exporter. Returns the number of waves run. Safe alongside a
        concurrent run()/step() driver — rounds serialize on
        `_wave_lock`, so the two loops cooperate on draining rather
        than racing the engine."""
        self.drain()
        waves = self.run(max_waves=max_waves)
        self.engine.stop_metrics_server()
        return waves

    def run(self, drain=True, max_waves=None):
        """Drive step() until the queue and all slots drain (or max_waves
        hit). Producer threads may keep submit()ing while this runs."""
        waves = 0
        while self.step():
            waves += 1
            if max_waves is not None and waves >= max_waves:
                break
        return waves

    # ---------------------------------------------------------- conveniences
    def generate(self, prompt, **kw):
        """Blocking single-request convenience (the create_llm_predictor
        surface): submit, drain, return the generated token list."""
        req = self.submit(prompt=prompt, **kw)
        while not req.done:
            self.step()
        return req.output_tokens
